"""Transport-independent API server core.

REST verbs (create/get/list/update/delete/watch) with the reference's
semantics (pkg/apiserver/resthandler.go):

- create: defaulting (uid, creationTimestamp, namespace, generateName),
  validation, AlreadyExists on duplicates.
- update: CAS when the client supplies metadata.resourceVersion,
  last-write-wins when it doesn't (reference allows both).
- list/watch: label & field selector filtering; lists carry the store
  version so watches can resume exactly after them.
- bind: the parity-critical guarded write — pod.spec.nodeName is set
  iff currently empty (pkg/registry/pod/etcd/etcd.go:123-181).
- update_status: status subresource writes that preserve spec.

All objects cross this boundary in wire form (camelCase dicts); typed
callers use the client layer.
"""

from __future__ import annotations

import contextlib
import json
import logging
import random
import string
import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple

from kubernetes_tpu.models import labels as labelpkg
from kubernetes_tpu.models import serde
from kubernetes_tpu.models.objects import now_iso, new_uid
from kubernetes_tpu.models.validation import ValidationError
from kubernetes_tpu.server.allocators import (
    AllocationError,
    IPAllocator,
    PortAllocator,
    service_ips_in_use,
    service_node_ports_in_use,
)
from kubernetes_tpu.server.registry import RESOURCES, ResourceInfo, fields_for
from kubernetes_tpu.store import (
    AlreadyExistsError,
    ConflictError,
    KVStore,
    NotFoundError,
)
from kubernetes_tpu.store.watch import WatchStream

_LOG = logging.getLogger("kubernetes_tpu.apiserver")

#: Default grace for the eviction subresource when the Eviction body
#: names none (reference: 30s pod default, scaled to this codebase's
#: test-sized clusters).
DEFAULT_EVICTION_GRACE_SECONDS = 5


class APIError(Exception):
    def __init__(self, code: int, reason: str, message: str):
        self.code = code
        self.reason = reason
        self.message = message
        super().__init__(message)

    def to_status(self) -> dict:
        return {
            "kind": "Status",
            "apiVersion": "v1",
            "status": "Failure",
            "reason": self.reason,
            "message": self.message,
            "code": self.code,
        }


def _pod_node_name(obj: dict) -> str:
    """Shared shard extractor: equal shards must hash together, so this
    is THE module-level callable for pod spec.nodeName routing."""
    return obj.get("spec", {}).get("nodeName", "") or ""


_SHARD_FIELDS = {("pods", "spec.nodeName"): _pod_node_name}


def _watch_shard(resource: str, field_selector: str):
    """Derive a dispatch-routing shard from a watch's field selector:
    an exact-equality clause on an indexed field (pods' spec.nodeName —
    the kubelet/scheduler watch shape) lets the store skip this
    watcher for events that can't concern it. Conservative: any parse
    surprise returns None (unindexed, full fan-out)."""
    if not field_selector:
        return None
    try:
        fsel = labelpkg.parse_fields(field_selector)
    except ValueError:
        return None
    for key, op, value in fsel.requirements:
        if op == labelpkg.EQUALS:
            fn = _SHARD_FIELDS.get((resource, key))
            if fn is not None:
                return (fn, value)
    return None


def _not_found(resource: str, name: str) -> APIError:
    return APIError(404, "NotFound", f'{resource} "{name}" not found')


def _conflict(msg: str) -> APIError:
    return APIError(409, "Conflict", msg)


def _invalid(msg: str) -> APIError:
    return APIError(422, "Invalid", msg)


def _json_merge(target: dict, patch: dict) -> dict:
    """RFC 7386 JSON merge patch: null deletes, dicts merge
    recursively, everything else replaces."""
    out = dict(target)
    for k, v in patch.items():
        if v is None:
            out.pop(k, None)
        elif isinstance(v, dict):
            # Merge into the existing dict, or into {} when the target
            # key is absent/non-dict — RFC 7386 strips nulls either way
            # (storing a literal null would make the key 'exist' and
            # break later null-delete semantics).
            base = out.get(k)
            out[k] = _json_merge(base if isinstance(base, dict) else {}, v)
        else:
            out[k] = v
    return out


def _json_pointer_parts(pointer: str) -> List[str]:
    """RFC 6901: '/a/b~1c/0' -> ['a', 'b/c', '0']."""
    if pointer == "":
        return []
    if not pointer.startswith("/"):
        raise _bad_request(f"invalid JSON pointer {pointer!r}")
    return [
        p.replace("~1", "/").replace("~0", "~")
        for p in pointer[1:].split("/")
    ]


def _json_patch(doc: dict, ops: list) -> dict:
    """RFC 6902 JSON patch: ordered add/remove/replace/move/copy/test
    over JSON pointers (the reference PATCH handler's JSONPatchType,
    pkg/apiserver/resthandler.go:446)."""
    import copy as _copy

    doc = _copy.deepcopy(doc)

    def resolve(pointer):
        """-> (container, final_token). Container is a dict or list.
        RFC 6902 resolution never auto-creates intermediates: 'add'
        (and move/copy targets) MUST fail when the parent container
        does not exist — matching evanphx/json-patch, which the
        reference vendors."""
        parts = _json_pointer_parts(pointer)
        if not parts:
            raise _bad_request("operations on the root document are not supported")
        cur = doc
        for p in parts[:-1]:
            if isinstance(cur, list):
                try:
                    cur = cur[int(p)]
                except (ValueError, IndexError):
                    raise _bad_request(f"pointer {pointer!r}: bad index {p!r}")
            elif isinstance(cur, dict):
                if p not in cur:
                    raise _bad_request(f"pointer {pointer!r}: missing {p!r}")
                cur = cur[p]
            else:
                raise _bad_request(f"pointer {pointer!r}: {p!r} is a scalar")
        return cur, parts[-1]

    def get_at(pointer):
        cont, tok = resolve(pointer)
        if isinstance(cont, list):
            try:
                return cont[int(tok)]
            except (ValueError, IndexError):
                raise _bad_request(f"pointer {pointer!r}: bad index")
        if tok not in cont:
            raise _bad_request(f"pointer {pointer!r}: missing {tok!r}")
        return cont[tok]

    def add_at(pointer, value):
        cont, tok = resolve(pointer)
        if isinstance(cont, list):
            if tok == "-":
                cont.append(value)
            else:
                try:
                    i = int(tok)
                except ValueError:
                    raise _bad_request(f"pointer {pointer!r}: bad index")
                if not 0 <= i <= len(cont):
                    raise _bad_request(f"pointer {pointer!r}: index out of range")
                cont.insert(i, value)
        else:
            cont[tok] = value

    def remove_at(pointer):
        cont, tok = resolve(pointer)
        if isinstance(cont, list):
            try:
                return cont.pop(int(tok))
            except (ValueError, IndexError):
                raise _bad_request(f"pointer {pointer!r}: bad index")
        if tok not in cont:
            raise _bad_request(f"pointer {pointer!r}: missing {tok!r}")
        return cont.pop(tok)

    for op in ops:
        if not isinstance(op, dict) or "op" not in op or "path" not in op:
            raise _bad_request("each patch op needs 'op' and 'path'")
        kind, path = op["op"], op["path"]
        if kind == "add":
            add_at(path, _copy.deepcopy(op.get("value")))
        elif kind == "replace":
            remove_at(path)
            add_at(path, _copy.deepcopy(op.get("value")))
        elif kind == "remove":
            remove_at(path)
        elif kind == "move":
            add_at(path, remove_at(op.get("from", "")))
        elif kind == "copy":
            add_at(path, _copy.deepcopy(get_at(op.get("from", ""))))
        elif kind == "test":
            if get_at(path) != op.get("value"):
                raise APIError(
                    409, "Conflict", f"test failed at {path!r}"
                )
        else:
            raise _bad_request(f"unknown patch op {kind!r}")
    return doc


#: Strategic-merge list merge keys, keyed on the FIELD NAME the list
#: lives under — mirroring the reference's per-field struct tags
#: consumed by pkg/util/strategicpatch (`patchMergeKey`), not a global
#: candidate order. Container ports must merge by containerPort even
#: when every element also carries a name: a patch entry reusing a
#: name with a new containerPort APPENDS in the reference (distinct
#: merge-key value) rather than updating in place.
_FIELD_MERGE_KEYS: Dict[str, Tuple[str, ...]] = {
    "containers": ("name",),
    "env": ("name",),
    "volumes": ("name",),
    "imagePullSecrets": ("name",),
    "volumeMounts": ("mountPath",),
    # Container ports merge by containerPort; Service ports (same
    # field name, no containerPort on the elements) by port.
    "ports": ("containerPort", "port"),
    # Two element shapes share this field name: Endpoints subset
    # addresses (keyed by ip, pkg/api/types.go EndpointAddress) and
    # NodeStatus addresses (keyed by type, NodeAddress has no ip
    # field) — candidates in struct-tag order, first present wins.
    "addresses": ("ip", "type"),
    "conditions": ("type",),
    "secrets": ("name",),
}
#: Fallback candidates for lists under fields with no registered tag.
_STRATEGIC_MERGE_KEYS = ("name", "containerPort", "port", "mountPath", "type", "ip")


def _strategic_key_for(items: list, field: Optional[str] = None) -> Optional[str]:
    if not items or not all(isinstance(x, dict) for x in items):
        return None
    candidates = _FIELD_MERGE_KEYS.get(field) if field else None
    for key in candidates if candidates else _STRATEGIC_MERGE_KEYS:
        if all(key in x for x in items):
            return key
    return None


def _strategic_merge(target: dict, patch: dict) -> dict:
    """Strategic merge patch (pkg/util/strategicpatch): like RFC 7386
    but lists of objects MERGE element-wise by their merge key instead
    of replacing wholesale; a '$patch': 'delete' element removes its
    match, '$patch': 'replace' in a dict replaces it wholesale."""
    if patch.get("$patch") == "replace":
        out = {k: v for k, v in patch.items() if k != "$patch"}
        return out
    out = dict(target)
    for k, v in patch.items():
        if v is None:
            out.pop(k, None)
        elif isinstance(v, dict):
            base = out.get(k)
            out[k] = _strategic_merge(base if isinstance(base, dict) else {}, v)
        elif isinstance(v, list):
            base = out.get(k)
            key = _strategic_key_for(
                [x for x in v if isinstance(x, dict) and x.get("$patch") != "delete"],
                field=k,
            ) or _strategic_key_for(base if isinstance(base, list) else [], field=k)
            if key is None or not isinstance(base, list):
                out[k] = [
                    x for x in v
                    if not (isinstance(x, dict) and x.get("$patch") == "delete")
                ]
                continue
            merged = list(base)
            index = {
                x.get(key): i
                for i, x in enumerate(merged)
                if isinstance(x, dict)
            }
            for item in v:
                if not isinstance(item, dict) or key not in item:
                    # A $patch directive MUST carry the list's merge
                    # key (reference strategicpatch errors likewise);
                    # appending it raw would persist the directive
                    # into the stored object and skip the delete.
                    if isinstance(item, dict) and "$patch" in item:
                        raise _bad_request(
                            f"strategic patch directive in {k!r} lacks "
                            f"merge key {key!r}"
                        )
                    merged.append(item)
                    continue
                i = index.get(item[key])
                if item.get("$patch") == "delete":
                    if i is not None:
                        merged[i] = None  # compact below
                    continue
                if i is None:
                    merged.append(item)
                    index[item[key]] = len(merged) - 1
                else:
                    merged[i] = _strategic_merge(
                        merged[i] if isinstance(merged[i], dict) else {}, item
                    )
            out[k] = [x for x in merged if x is not None]
        else:
            out[k] = v
    return out


def _bad_request(msg: str) -> APIError:
    return APIError(400, "BadRequest", msg)


class APIServer:
    """The master: storage-backed REST resources (pkg/master/master.go)."""

    def __init__(
        self,
        store: Optional[KVStore] = None,
        admission=None,
        service_cidr: str = "10.0.0.0/24",
        node_port_range: Tuple[int, int] = (30000, 32767),
    ):
        self.store = store or KVStore()
        # Watch-cache read path (server/watchcache.py): per-resource
        # event-fed mirrors serving GET/LIST without touching kvstore
        # or re-serializing. Lazily built per resource on first LIST.
        from kubernetes_tpu.server.watchcache import WatchCacheSet

        self.caches = WatchCacheSet(self.store)
        # Lifecycle SLI collector (utils/sli.py): the process-global
        # collector rides the SAME dispatcher feed as the watch cache —
        # pod events become pod_startup_latency_seconds milestone
        # watermarks with zero polling and zero extra copies. Always
        # on (tests/test_sli.py pins its cost under 5% of the bulk
        # churn drill's per-pod budget).
        from kubernetes_tpu.utils import sli

        sli.DEFAULT.attach(self.store)
        # Reentrant: admission plugins may issue writes of their own
        # (NamespaceAutoprovision creates the namespace mid-admission).
        from kubernetes_tpu.utils import sanitizer

        self._lock = sanitizer.rlock("apiserver.state")
        self._rand = random.Random(0xC0FFEE)
        # Admission chain (kubernetes_tpu.server.admission.Chain); None
        # means admit everything (reference default --admission-control
        # AlwaysAdmit, cmd/kube-apiserver/app/server.go:117).
        self.admission = admission
        # Live component health checks (componentstatuses probes on
        # read; pkg/registry/componentstatus/rest.go).
        self._component_checks: Dict[str, object] = {}
        # HA control plane handle (store/replication.py): a
        # ReplicationHub when this apiserver fronts the leader store, a
        # FollowerReplica when it fronts a replica. Drives the /healthz
        # replication subcheck, /replication/append ingest, and the
        # follower's mutating-verb forward (httpserver.py). None =
        # single-node, the historical shape.
        self.replication = None
        # A follower apiserver forwards writes here (the leader's base
        # URL); set alongside `replication` by the HA wiring.
        self.leader_url = ""
        # Service allocation pools (pkg/master/master.go:440-455) with
        # the reference's restart repair pass: rebuild the bitmaps from
        # whatever services the (possibly pre-existing) store holds
        # (ipallocator/controller/repair.go).
        self.service_ips = IPAllocator(service_cidr)
        self.service_node_ports = PortAllocator(*node_port_range)
        stored_services, _ = self.store.list("/registry/services/")
        for ip in service_ips_in_use(stored_services):
            self.service_ips.mark(ip)
        for port in service_node_ports_in_use(stored_services):
            self.service_node_ports.mark(port)
        # Ensure the default namespace exists (reference auto-creates).
        # A replica-mode store is read-only from this side — the
        # namespace arrives through replication from the leader.
        if getattr(self.store, "replica", False):
            return
        try:
            self.store.create(
                "/registry/namespaces/default",
                {
                    "kind": "Namespace",
                    "apiVersion": "v1",
                    "metadata": {
                        "name": "default",
                        "uid": new_uid(),
                        "creationTimestamp": now_iso(),
                    },
                    "spec": {},
                    "status": {"phase": "Active"},
                },
            )
        except AlreadyExistsError:
            pass

    # -- helpers ------------------------------------------------------

    def _info(self, resource: str) -> ResourceInfo:
        info = RESOURCES.get(resource)
        if info is None:
            raise _bad_request(f"unknown resource {resource!r}")
        return info

    def _gen_name(self, base: str) -> str:
        suffix = "".join(self._rand.choices(string.ascii_lowercase + "0123456789", k=5))
        return base + suffix

    # -- verbs --------------------------------------------------------

    def create(self, resource: str, namespace: str, obj: dict) -> dict:
        info = self._info(resource)
        if info.name == "namespaces":
            # Reference: namespaces default to the "kubernetes" finalizer
            # (pkg/registry/namespace/etcd + pkg/api defaults), making
            # deletion two-phase (Terminating -> content purge -> gone).
            obj.setdefault("spec", {}).setdefault("finalizers", ["kubernetes"])
            obj.setdefault("status", {}).setdefault("phase", "Active")
        ns, _name = self._default_create_meta(info, namespace, obj)
        meta = obj["metadata"]
        with self._write_guard():
            self._admit("CREATE", info, ns, meta["name"], obj)
            self._validate(info, obj)
            release = (
                self._allocate_service(obj) if info.name == "services" else None
            )
            try:
                out = self.store.create(
                    info.key(ns, meta["name"]), obj, ttl=info.ttl
                )
            except AlreadyExistsError:
                if release:
                    release()
                raise _conflict(f'{info.name} "{meta["name"]}" already exists')
            self._commit("CREATE", info, ns, meta["name"], obj)
            return out

    def _write_guard(self):
        """Serialize admission's check-then-act with the store write so
        concurrent requests cannot both pass a quota/limit check and
        blow past a hard limit (the reference serializes via CAS on
        quota status; an in-process lock is the equivalent here). A
        no-op when no admission chain is configured."""
        if self.admission is None:
            return contextlib.nullcontext()
        return self._lock

    def _admit(
        self, operation: str, info: ResourceInfo, ns: str, name: str, obj
    ) -> None:
        if self.admission is None:
            return
        from kubernetes_tpu.server.admission import AdmissionError, Attributes

        try:
            self.admission.admit(
                Attributes(
                    operation=operation,
                    resource=info.name,
                    namespace=ns,
                    name=name,
                    obj=obj,
                )
            )
        except AdmissionError as e:
            raise APIError(e.code, e.reason, e.message)

    def _commit(
        self, operation: str, info: ResourceInfo, ns: str, name: str, obj
    ) -> None:
        """Post-write admission hook (usage bookkeeping); never raises."""
        if self.admission is None:
            return
        from kubernetes_tpu.server.admission import Attributes

        try:
            self.admission.commit(
                Attributes(
                    operation=operation,
                    resource=info.name,
                    namespace=ns,
                    name=name,
                    obj=obj,
                )
            )
        except Exception:
            # Usage bookkeeping drift is better logged than hidden —
            # the write itself already succeeded, so don't fail it.
            _LOG.exception("post-write admission commit failed")

    def _validate(self, info: ResourceInfo, obj: dict) -> None:
        if info.validator is None:
            return
        typed = serde.from_wire(info.cls, obj)
        try:
            info.validator(typed)
        except ValidationError as e:
            raise _invalid("; ".join(e.errors))

    def _validate_fast(self, info: ResourceInfo, obj: dict) -> None:
        """Bulk-path validation: the wire-form twin when the resource
        registers one (same checks, no typed decode — the decode was
        the apiserver's largest per-pod cost at bulk rates), otherwise
        the ordinary typed validator."""
        if info.wire_validator is not None:
            try:
                info.wire_validator(obj)
            except ValidationError as e:
                raise _invalid("; ".join(e.errors))
            return
        self._validate(info, obj)

    def _ns(self, info: ResourceInfo, namespace: str) -> str:
        return (namespace or "default") if info.namespaced else ""

    # -- service allocation (pkg/registry/service/rest.go:68-131) ------

    def _allocate_service(self, obj: dict):
        """Fill spec.clusterIP / spec.ports[].nodePort from the pools.
        Returns a rollback closure releasing everything granted, for
        the store-create-failed path (rest.go's releaseServiceIP defer
        + portallocator operation)."""
        spec = obj.setdefault("spec", {})
        granted_ip: Optional[str] = None
        granted_ports: List[int] = []

        def rollback():
            if granted_ip:
                self.service_ips.release(granted_ip)
            for p in granted_ports:
                self.service_node_ports.release(p)

        try:
            ip = spec.get("clusterIP") or ""
            if not ip:
                spec["clusterIP"] = granted_ip = self.service_ips.allocate_next()
            elif ip != "None":
                self.service_ips.allocate(ip)
                granted_ip = ip
            assign = spec.get("type") in ("NodePort", "LoadBalancer")
            for port in spec.get("ports") or []:
                requested = port.get("nodePort") or 0
                if requested:
                    self.service_node_ports.allocate(requested)
                    granted_ports.append(requested)
                elif assign:
                    port["nodePort"] = self.service_node_ports.allocate_next()
                    granted_ports.append(port["nodePort"])
        except AllocationError as e:
            rollback()
            raise _invalid(f"spec.clusterIP/nodePort: {e}")
        return rollback

    @staticmethod
    def _carry_node_ports(cur_spec: dict, new_spec: dict) -> None:
        """Fill missing nodePort fields on an updated/patched spec from
        the current object, matching ports by name (or by port number
        when unnamed) — the reference's update path carries the
        existing allocation over rather than churning the externally
        advertised port on every full replace."""
        by_key = {}
        for p in cur_spec.get("ports") or []:
            if p.get("nodePort"):
                by_key[p.get("name") or ("#", p.get("port"))] = p["nodePort"]
        claimed = {
            p.get("nodePort") for p in new_spec.get("ports") or [] if p.get("nodePort")
        }
        for p in new_spec.get("ports") or []:
            if p.get("nodePort"):
                continue
            prev = by_key.get(p.get("name") or ("#", p.get("port")))
            if prev and prev not in claimed:
                p["nodePort"] = prev
                claimed.add(prev)

    def _update_service_allocations(self, current: dict, obj: dict):
        """Update-path allocation semantics: clusterIP is immutable
        (carried over when omitted, rejected when changed — reference
        validation.ValidateServiceUpdate); existing node ports carry
        over, newly requested ones allocate, dropped ones release only
        after the write commits. Returns (rollback, commit) closures."""
        cur_spec = current.get("spec") or {}
        spec = obj.setdefault("spec", {})
        cur_ip = cur_spec.get("clusterIP") or ""
        new_ip = spec.get("clusterIP") or ""
        if not new_ip and cur_ip:
            spec["clusterIP"] = cur_ip
        elif cur_ip and new_ip != cur_ip:
            raise _invalid("spec.clusterIP: field is immutable")
        cur_ports = {
            p.get("nodePort") for p in cur_spec.get("ports") or [] if p.get("nodePort")
        }
        granted: List[int] = []
        assign = spec.get("type") in ("NodePort", "LoadBalancer")
        if assign:
            # Only a type that still wants node ports carries them over;
            # NodePort -> ClusterIP must shed its ports (commit()
            # releases them) instead of pinning them forever.
            self._carry_node_ports(cur_spec, spec)
        else:
            # Shed explicitly-submitted stale ports too: a ClusterIP
            # spec has no business carrying nodePort fields, and
            # leaving them would keep the pool allocation forever.
            for p in spec.get("ports") or []:
                p.pop("nodePort", None)
        try:
            new_ports = set()
            for port in spec.get("ports") or []:
                requested = port.get("nodePort") or 0
                if not requested and assign:
                    port["nodePort"] = requested = (
                        self.service_node_ports.allocate_next()
                    )
                    granted.append(requested)
                elif requested and requested not in cur_ports:
                    self.service_node_ports.allocate(requested)
                    granted.append(requested)
                if requested:
                    new_ports.add(requested)
        except AllocationError as e:
            for p in granted:
                self.service_node_ports.release(p)
            raise _invalid(f"spec.ports.nodePort: {e}")

        def rollback():
            for p in granted:
                self.service_node_ports.release(p)

        def commit():
            for p in cur_ports - new_ports:
                self.service_node_ports.release(p)

        return rollback, commit

    def publish_master_service(self, host: str, port: int) -> dict:
        """Publish the 'kubernetes' service + endpoints addressing this
        master (pkg/master/publish.go). Selector-less, so the endpoints
        controller leaves the manually-set endpoints alone; reconciled
        on every (re)start so a moved master updates its address."""
        try:
            svc = self.get("services", "default", "kubernetes")
            if (svc.get("spec") or {}).get("ports") != [
                {"name": "http", "port": port, "protocol": "TCP"}
            ]:
                # Master restarted on a different port over a persisted
                # store: the advertised service port must follow.
                svc["spec"]["ports"] = [
                    {"name": "http", "port": port, "protocol": "TCP"}
                ]
                svc = self.update("services", "default", "kubernetes", svc)
        except APIError:
            svc = self.create(
                "services",
                "default",
                {
                    "kind": "Service",
                    "apiVersion": "v1",
                    "metadata": {"name": "kubernetes", "namespace": "default"},
                    "spec": {
                        "ports": [{"name": "http", "port": port, "protocol": "TCP"}],
                        "sessionAffinity": "None",
                    },
                },
            )
        endpoints = {
            "kind": "Endpoints",
            "apiVersion": "v1",
            "metadata": {"name": "kubernetes", "namespace": "default"},
            "subsets": [
                {
                    "addresses": [{"ip": host}],
                    "ports": [{"name": "http", "port": port, "protocol": "TCP"}],
                }
            ],
        }
        try:
            self.update("endpoints", "default", "kubernetes", endpoints)
        except APIError as e:
            if e.code != 404:
                raise
            self.create("endpoints", "default", endpoints)
        return svc

    def _release_service(self, obj: dict) -> None:
        spec = obj.get("spec") or {}
        ip = spec.get("clusterIP") or ""
        if ip and ip != "None":
            self.service_ips.release(ip)
        for port in spec.get("ports") or []:
            if port.get("nodePort"):
                self.service_node_ports.release(port["nodePort"])

    # -- component statuses (live health probes) ----------------------

    def register_component(self, name: str, check) -> None:
        """Register a component health check (callable -> (ok, msg)).
        Reference: pkg/registry/componentstatus/rest.go — the resource
        is a LIVE view probing registered servers on every read, not
        stored objects."""
        self._component_checks[name] = check

    def _component_status(self, name: str) -> dict:
        check = self._component_checks[name]
        try:
            ok, msg = check()
        except Exception as e:
            ok, msg = False, f"{type(e).__name__}: {e}"
        return {
            "kind": "ComponentStatus",
            "apiVersion": "v1",
            "metadata": {"name": name},
            "conditions": [
                {
                    "type": "Healthy",
                    "status": "True" if ok else "False",
                    "message": msg,
                }
            ],
        }

    def get(self, resource: str, namespace: str, name: str) -> dict:
        info = self._info(resource)
        if info.name == "componentstatuses" and name in self._component_checks:
            return self._component_status(name)
        try:
            return self.store.get(info.key(self._ns(info, namespace), name))
        except NotFoundError:
            raise _not_found(info.name, name)

    def _cache_list(self, info: ResourceInfo, namespace: str):
        """(object REFS, version) through the watch cache when it is
        fresh, falling back to a direct store scan when the dispatcher
        trails too far (wedged fan-out must degrade, not error)."""
        cache = self.caches.cache_for(info.prefix())
        if cache.fresh():
            return cache.list_refs(info.prefix(namespace))
        return self.store.list(info.prefix(namespace), copy=False)

    def list(
        self,
        resource: str,
        namespace: str = "",
        label_selector: str = "",
        field_selector: str = "",
        copy: bool = True,
    ) -> dict:
        """Served from the watch cache (event-fed, read-your-writes via
        the version wait) — a LIST never scans or re-copies kvstore
        state on the steady-state path.

        copy=False returns the cache's own objects (READ-ONLY — for
        callers that immediately serialize, like the HTTP tier: a
        3000-pod LIST must not pay a full deep copy just to be JSON-
        encoded and thrown away). Stored objects are never mutated in
        place, so the refs are a consistent snapshot."""
        info = self._info(resource)
        items, version = self._cache_list(info, namespace)
        pred = self._selector_pred(resource, label_selector, field_selector)
        items = [o for o in items if pred(o)]
        if copy:
            from kubernetes_tpu.store.kvstore import _copy_obj

            items = [_copy_obj(o) for o in items]
        if info.name == "componentstatuses" and self._component_checks:
            # Live probes first (the reference ignores selectors here
            # entirely, rest.go:52; we at least apply them uniformly);
            # stored objects only fill names no live check covers.
            live = [
                o
                for n in sorted(self._component_checks)
                if pred(o := self._component_status(n))
            ]
            covered = set(self._component_checks)
            items = live + [
                o for o in items if o.get("metadata", {}).get("name") not in covered
            ]
        return {
            "kind": info.kind + "List",
            "apiVersion": "v1",
            "metadata": {"resourceVersion": str(version)},
            "items": items,
        }

    def list_response_bytes(
        self,
        resource: str,
        namespace: str = "",
        label_selector: str = "",
        field_selector: str = "",
    ) -> Optional[bytes]:
        """Complete JSON LIST response assembled from the watch cache's
        per-object encodings (each object serialized at most once per
        resourceVersion, ever — across LISTs, watchers, and callers).
        None when the fast path does not apply (live componentstatuses,
        stale cache) — the caller falls back to list()."""
        info = self._info(resource)
        if info.name == "componentstatuses" and self._component_checks:
            return None
        cache = self.caches.cache_for(info.prefix())
        if not cache.fresh():
            return None
        pred = None
        if label_selector or field_selector:
            pred = self._selector_pred(
                resource, label_selector, field_selector
            )
        body, _count, version = cache.list_encoded(
            info.prefix(namespace), pred
        )
        head = (
            '{"kind": "%sList", "apiVersion": "v1", "metadata": '
            '{"resourceVersion": "%d"}, "items": [' % (info.kind, version)
        ).encode()
        return head + body + b"]}"

    def get_response_bytes(
        self, resource: str, namespace: str, name: str
    ) -> Optional[bytes]:
        """Encoded GET served from the watch cache; None = fall back
        (missing object included — the slow path owns 404 semantics)."""
        info = self._info(resource)
        if info.name == "componentstatuses":
            return None
        cache = self.caches.cache_for(info.prefix())
        if not cache.fresh():
            return None
        return cache.get_encoded(info.key(self._ns(info, namespace), name))

    def _selector_pred(self, resource: str, label_selector: str, field_selector: str):
        lsel = labelpkg.parse(label_selector)
        fsel = labelpkg.parse_fields(field_selector)
        if lsel.empty() and fsel.empty():
            return lambda o: True

        def pred(o: dict) -> bool:
            if not lsel.empty():
                if not lsel.matches(o.get("metadata", {}).get("labels", {})):
                    return False
            if not fsel.empty():
                if not fsel.matches(fields_for(resource, o)):
                    return False
            return True

        return pred

    def update(self, resource: str, namespace: str, name: str, obj: dict) -> dict:
        info = self._info(resource)
        meta = obj.setdefault("metadata", {})
        if meta.get("name") and meta["name"] != name:
            raise _bad_request(f"name mismatch: body {meta['name']!r} vs url {name!r}")
        meta["name"] = name
        namespace = self._ns(info, namespace)
        if info.namespaced:
            meta.setdefault("namespace", namespace)
        key = info.key(namespace, name)
        try:
            current = self.store.get(key)
        except NotFoundError:
            raise _not_found(info.name, name)
        # Immutable server-side fields carry over.
        meta["uid"] = current["metadata"].get("uid", "")
        meta["creationTimestamp"] = current["metadata"].get("creationTimestamp", "")
        expected = None
        if meta.get("resourceVersion"):
            try:
                expected = int(meta["resourceVersion"])
            except ValueError:
                raise _bad_request(
                    f"invalid resourceVersion {meta['resourceVersion']!r}"
                )
        with self._write_guard():
            self._admit("UPDATE", info, namespace, name, obj)
            self._validate(info, obj)
            rollback = commit = None
            if info.name == "services":
                rollback, commit = self._update_service_allocations(current, obj)
            try:
                out = self.store.set(key, obj, expected_version=expected)
            except ConflictError as e:
                if rollback:
                    rollback()
                raise _conflict(str(e))
            except NotFoundError:
                if rollback:
                    rollback()
                raise _not_found(info.name, name)
            if commit:
                commit()
            self._commit("UPDATE", info, namespace, name, obj)
            return out

    def _mark_namespace_terminating(self, name: str) -> Optional[dict]:
        """Two-phase namespace deletion (pkg/registry/namespace/etcd):
        while spec.finalizers is non-empty, DELETE marks the namespace
        Terminating (deletionTimestamp + status.phase) and returns it;
        the namespace controller purges content, finalizes, and re-issues
        the DELETE which then actually removes the object. Returns None
        when the namespace should be deleted for real."""
        key = "/registry/namespaces/" + name
        try:
            cur = self.store.get(key)
        except NotFoundError:
            raise _not_found("namespaces", name)
        if not cur.get("spec", {}).get("finalizers"):
            return None

        def mark(obj: dict) -> dict:
            obj.setdefault("metadata", {}).setdefault(
                "deletionTimestamp", now_iso()
            )
            obj.setdefault("status", {})["phase"] = "Terminating"
            return obj

        try:
            return self.store.guaranteed_update(key, mark)
        except NotFoundError:
            raise _not_found("namespaces", name)

    def finalize_namespace(self, name: str, obj: dict) -> dict:
        """The 'finalize' subresource: replace spec.finalizers from the
        wire body (pkg/registry/namespace/etcd FinalizeREST)."""
        finalizers = list(obj.get("spec", {}).get("finalizers", []))

        def apply(cur: dict) -> dict:
            cur.setdefault("spec", {})["finalizers"] = finalizers
            return cur

        try:
            return self.store.guaranteed_update(
                "/registry/namespaces/" + name, apply
            )
        except NotFoundError:
            raise _not_found("namespaces", name)

    def connect(
        self, resource: str, namespace: str, name: str, subresource: str
    ) -> None:
        """Admission gate for CONNECT subresources (exec/attach/proxy).
        Reference: CONNECT verbs in pkg/apiserver/api_installer.go:268-284
        pass through the admission chain before upgrade."""
        info = self._info(resource)
        if self.admission is None:
            return
        from kubernetes_tpu.server.admission import AdmissionError, Attributes

        try:
            self.admission.admit(
                Attributes(
                    operation="CONNECT",
                    resource=info.name,
                    namespace=self._ns(info, namespace),
                    name=name,
                    subresource=subresource,
                )
            )
        except AdmissionError as e:
            raise APIError(e.code, e.reason, e.message)

    def patch(
        self,
        resource: str,
        namespace: str,
        name: str,
        patch,
        patch_type: str = "merge",
    ) -> dict:
        """PATCH with all three reference patch types
        (pkg/apiserver/resthandler.go:446): "merge" (RFC 7386, a dict),
        "json" (RFC 6902, an op list), "strategic" (strategic merge —
        lists of objects merge by key). Applied over a CAS retry.
        Admission runs on the MERGED object like any other update — a
        patch must not be a side door around quota/policy."""
        import copy as _copy

        info = self._info(resource)
        ns = self._ns(info, namespace)
        if patch_type not in ("merge", "json", "strategic"):
            raise _bad_request(f"unknown patch type {patch_type!r}")
        if patch_type == "json":
            if not isinstance(patch, list):
                raise _bad_request("a JSON patch body must be an op array")
        elif not isinstance(patch, dict):
            raise _bad_request("a merge patch body must be an object")
        # Deep copy: the sanitizer below edits nested dicts, and
        # in-process (LocalTransport) callers must get their patch
        # object back untouched.
        patch = _copy.deepcopy(patch)
        if patch_type != "json":
            # Identity/shape fields never come from a patch body.
            for forbidden in ("kind", "apiVersion"):
                patch.pop(forbidden, None)
            meta_patch = patch.get("metadata")
            if isinstance(meta_patch, dict):
                for forbidden in ("name", "namespace", "resourceVersion", "uid"):
                    meta_patch.pop(forbidden, None)

        pre: List[Optional[dict]] = [None]

        def apply(cur: dict) -> dict:
            pre[0] = _copy.deepcopy(cur)
            if patch_type == "json":
                merged = _json_patch(cur, patch)
            elif patch_type == "strategic":
                merged = _strategic_merge(cur, patch)
            else:
                merged = _json_merge(cur, patch)
            if not isinstance(merged, dict):
                raise _bad_request("patched object must remain an object")
            if not isinstance(merged.get("metadata", {}), dict):
                raise _bad_request("patched metadata must remain an object")
            # Identity fields are never patchable, whatever the type
            # (a JSON patch op can name any pointer — restore).
            for field in ("kind", "apiVersion"):
                if field in cur:
                    merged[field] = cur[field]
            m_cur = cur.get("metadata") or {}
            m_new = merged.setdefault("metadata", {})
            for field in ("name", "namespace", "resourceVersion", "uid"):
                if field in m_cur:
                    m_new[field] = m_cur[field]
                else:
                    m_new.pop(field, None)
            if info.name == "services":
                # PATCH must not be a side door around the allocator
                # invariants create/update enforce: clusterIP stays
                # immutable; existing nodePorts carry over when the
                # patch replaces spec.ports; a patched-in nodePort must
                # be in range and free; a NodePort service port cannot
                # be left without one.
                cur_spec, new_spec = cur.get("spec") or {}, merged.get("spec") or {}
                cur_ip = cur_spec.get("clusterIP") or ""
                new_ip = new_spec.get("clusterIP") or ""
                if cur_ip and new_ip != cur_ip:
                    raise _invalid("spec.clusterIP: field is immutable")
                assign = new_spec.get("type") in ("NodePort", "LoadBalancer")
                if assign:
                    self._carry_node_ports(cur_spec, new_spec)
                else:
                    # Type patched away from NodePort: the merge kept
                    # the old ports (with nodePorts) — shed them so the
                    # post-commit reconcile releases the pool slots.
                    for p in new_spec.get("ports") or []:
                        p.pop("nodePort", None)
                held = {
                    p.get("nodePort")
                    for p in cur_spec.get("ports") or []
                    if p.get("nodePort")
                }
                lo, hi = self.service_node_ports.lo, self.service_node_ports.hi
                for p in new_spec.get("ports") or []:
                    np = p.get("nodePort") or 0
                    if np and not (lo <= np <= hi):
                        raise _invalid(
                            f"spec.ports.nodePort: port {np} is not in the "
                            f"node port range {lo}-{hi}"
                        )
                    if np and np not in held and self.service_node_ports.is_allocated(np):
                        raise _invalid(f"spec.ports.nodePort: port {np} is already allocated")
                    if not np and assign:
                        raise _invalid(
                            "spec.ports.nodePort: a NodePort service port "
                            "needs an explicit nodePort when patched"
                        )
            self._admit("UPDATE", info, ns, name, merged)
            self._validate(info, merged)
            return merged

        key = info.key(ns, name)
        with self._write_guard():
            try:
                out = self.store.guaranteed_update(key, apply)
            except NotFoundError:
                raise _not_found(info.name, name)
            if info.name == "services":
                # Reconcile the port pool with what actually committed.
                def _ports(o):
                    return {
                        p.get("nodePort")
                        for p in (o.get("spec") or {}).get("ports") or []
                        if p.get("nodePort")
                    }

                old_ports, new_ports = _ports(pre[0] or {}), _ports(out)
                for p in new_ports - old_ports:
                    self.service_node_ports.mark(p)
                for p in old_ports - new_ports:
                    self.service_node_ports.release(p)
            self._commit("UPDATE", info, ns, name, out)
        return out

    def service_location(
        self, namespace: str, name: str, port_hint: str = ""
    ) -> Tuple[str, int]:
        """Pick a backend (ip, port) for a service — the routing half
        of the services proxy subresource (reference:
        pkg/registry/service/rest.go ResourceLocation: resolve the
        service's endpoints, pick a random one). `port_hint` from the
        'name:port' form selects by endpoint port name (or number);
        empty takes the first port."""
        try:
            eps = self.get("endpoints", namespace, name)
        except APIError as e:
            if e.code != 404:
                raise
            # Distinguish "service doesn't exist" (404) from "exists
            # but has no endpoints yet" (503).
            self.get("services", namespace, name)
            eps = {}
        candidates: List[Tuple[str, int]] = []
        for subset in eps.get("subsets") or []:
            ports = subset.get("ports") or []
            chosen = None
            if not port_hint:
                chosen = ports[0]["port"] if ports else None
            elif port_hint.isdigit():
                if any(p.get("port") == int(port_hint) for p in ports):
                    chosen = int(port_hint)
            else:
                for p in ports:
                    if p.get("name") == port_hint:
                        chosen = p["port"]
                        break
            if chosen is None:
                continue
            for addr in subset.get("addresses") or []:
                if addr.get("ip"):
                    candidates.append((addr["ip"], chosen))
        if not candidates:
            raise APIError(
                503,
                "ServiceUnavailable",
                f"no endpoints available for service {name!r}",
            )
        return candidates[self._rand.randrange(len(candidates))]

    def kubelet_location(self, namespace: str, name: str) -> Tuple[str, dict]:
        """Resolve the kubelet API base URL serving a pod — the routing
        half of the log/exec subresources (reference: LogLocation /
        ExecLocation in pkg/registry/pod/rest.go resolve node host +
        port 10250; we read the port from NodeStatus daemon endpoints).
        Returns (base_url, pod_wire)."""
        pod = self.get("pods", namespace, name)
        node_name = pod.get("spec", {}).get("nodeName", "")
        if not node_name:
            raise APIError(
                409, "Conflict", f"pod {name!r} is not scheduled to a node yet"
            )
        node = self.get("nodes", "", node_name)
        status = node.get("status", {})
        port = (
            status.get("daemonEndpoints", {})
            .get("kubeletEndpoint", {})
            .get("port", 0)
        )
        if not port:
            raise APIError(
                501,
                "NotImplemented",
                f"node {node_name!r} does not publish a kubelet API endpoint",
            )
        ip = next(
            (
                a.get("address")
                for a in status.get("addresses", [])
                if a.get("type") == "InternalIP"
            ),
            "127.0.0.1",
        )
        return f"http://{ip}:{port}", pod

    def _pod_container(self, pod: dict, container: str) -> str:
        if container:
            return container
        containers = pod.get("spec", {}).get("containers", [])
        return containers[0].get("name", "") if containers else ""

    def pod_log(
        self,
        namespace: str,
        name: str,
        container: str = "",
        tail: Optional[int] = None,
    ) -> str:
        """GET /pods/{name}/log — relayed from the pod's kubelet
        (reference: LogREST, pkg/registry/pod/etcd/etcd.go:45)."""
        import urllib.error
        import urllib.request

        base, pod = self.kubelet_location(namespace, name)
        container = self._pod_container(pod, container)
        url = f"{base}/logs/{namespace or 'default'}/{name}/{container}"
        if tail is not None:
            url += f"?tail={int(tail)}"
        try:
            with urllib.request.urlopen(url, timeout=10) as resp:
                return resp.read().decode(errors="replace")
        except urllib.error.URLError as e:
            raise APIError(502, "BadGateway", f"kubelet log fetch failed: {e}")

    def pod_exec(
        self, namespace: str, name: str, container: str, body: dict
    ) -> dict:
        """POST /pods/{name}/exec — admission-gated, then relayed to the
        pod's kubelet as JSON run-style exec (reference: ExecLocation +
        pkg/kubelet/server.go /exec/)."""
        import urllib.error
        import urllib.request

        self.connect("pods", namespace, name, "exec")
        command = (body or {}).get("command", [])
        if not command:
            raise _bad_request("exec requires a command")
        base, pod = self.kubelet_location(namespace, name)
        container = self._pod_container(pod, container)
        url = f"{base}/exec/{namespace or 'default'}/{name}/{container}"
        req = urllib.request.Request(
            url,
            data=json.dumps({"command": command}).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                return json.loads(resp.read())
        except urllib.error.URLError as e:
            raise APIError(502, "BadGateway", f"kubelet exec failed: {e}")

    def update_status(self, resource: str, namespace: str, name: str, obj: dict) -> dict:
        """Status subresource: replace only .status (pkg/registry/pod/etcd
        StatusREST)."""
        info = self._info(resource)
        key = info.key(self._ns(info, namespace), name)
        new_status = obj.get("status", {})

        def apply(cur: dict) -> dict:
            cur["status"] = new_status
            return cur

        try:
            # atomic_update, not guaranteed_update: status writes are
            # the highest-traffic mutation (every kubelet sync), and
            # the single-hold form halves lock handoffs under burst.
            return self.store.atomic_update(key, apply)
        except NotFoundError:
            raise _not_found(info.name, name)

    def _mark_pod_terminating(
        self, namespace: str, name: str, grace: int
    ) -> Optional[dict]:
        """Graceful pod delete: instead of removing the object, stamp
        metadata.deletionTimestamp (= now + grace, the force-delete
        deadline) and deletionGracePeriodSeconds, so watchers see ONE
        MODIFIED (Terminating) now and ONE DELETED when the kubelet
        confirms termination with a grace-0 delete. A second graceful
        DELETE can only shorten the remaining grace, never extend it
        (reference: rest.BeforeDelete's CheckGracefulDelete shape).
        Returns the marked pod, or None when the pod should be removed
        immediately (unbound — no kubelet will ever confirm it)."""
        try:
            pod = self.store.get(RESOURCES["pods"].key(namespace, name))
        except NotFoundError:
            raise _not_found("pods", name)
        if not pod.get("spec", {}).get("nodeName"):
            return None  # pending pod: nothing to terminate gracefully

        deadline = time.time() + grace

        def mark(obj: dict) -> dict:
            meta = obj.setdefault("metadata", {})
            prev = meta.get("deletionTimestamp", "")
            new_ts = time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime(deadline)
            )
            if not prev or new_ts < prev:
                meta["deletionTimestamp"] = new_ts
                meta["deletionGracePeriodSeconds"] = grace
            return obj

        try:
            return self.store.guaranteed_update(
                RESOURCES["pods"].key(namespace, name), mark
            )
        except NotFoundError:
            raise _not_found("pods", name)

    def evict_pod(self, namespace: str, name: str, body: Optional[dict]) -> dict:
        """POST /pods/{name}/eviction — the graceful-delete subresource
        (shape follows policy/v1 Eviction: metadata + deleteOptions).
        The preemption path uses this so victims terminate with grace
        instead of vanishing under their kubelet."""
        body = body or {}
        opts = body.get("deleteOptions") or {}
        grace = opts.get("gracePeriodSeconds")
        if grace is None:
            grace = DEFAULT_EVICTION_GRACE_SECONDS
        try:
            grace = int(grace)
        except (TypeError, ValueError):
            raise _bad_request(
                f"deleteOptions.gracePeriodSeconds: invalid {grace!r}"
            )
        return self.delete(
            "pods", namespace, name, grace_period_seconds=grace
        )

    def delete(
        self,
        resource: str,
        namespace: str,
        name: str,
        grace_period_seconds: Optional[int] = None,
    ) -> dict:
        info = self._info(resource)
        if info.name == "namespaces":
            marked = self._mark_namespace_terminating(name)
            if marked is not None:
                return marked
        with self._write_guard():
            self._admit("DELETE", info, self._ns(info, namespace), name, None)
            if (
                info.name == "pods"
                and grace_period_seconds is not None
                and grace_period_seconds > 0
            ):
                # Bound-ness check and the immediate-delete fallback
                # stay under ONE guard hold: a bind landing between
                # them would otherwise hard-delete a pod the caller
                # asked to terminate gracefully.
                marked = self._mark_pod_terminating(
                    self._ns(info, namespace), name, int(grace_period_seconds)
                )
                if marked is not None:
                    return marked
                # Unbound pod: nothing to terminate — delete now.
            try:
                deleted = self.store.delete(info.key(self._ns(info, namespace), name))
            except NotFoundError:
                raise _not_found(info.name, name)
            if info.name == "services":
                self._release_service(deleted)
            self._commit("DELETE", info, self._ns(info, namespace), name, None)
        return {
            "kind": "Status",
            "apiVersion": "v1",
            "status": "Success",
            "code": 200,
        }

    def watch(
        self,
        resource: str,
        namespace: str = "",
        since: int = 0,
        label_selector: str = "",
        field_selector: str = "",
        maxsize: int = 4096,
    ) -> WatchStream:
        """Selector filtering happens INSIDE the store's fan-out (with
        etcd's modified-out-of-filter -> DELETED translation,
        kvstore._filter_event): a kubelet watching spec.nodeName=X never
        has the other nodes' pod events copied or queued for it.

        `maxsize` bounds the consumer's event queue (slow consumers are
        dropped at overflow and must re-list); bulk-churn clients ask
        for deeper buffers (?maxsize=) so a single group commit's burst
        of N events cannot out-run one scheduling quantum of their
        reader."""
        info = self._info(resource)
        pred = None
        shard = None
        if label_selector or field_selector:
            pred = self._selector_pred(resource, label_selector, field_selector)
            shard = _watch_shard(resource, field_selector)
        try:
            return self.store.watch(
                info.prefix(namespace), since=since, pred=pred, shard=shard,
                maxsize=max(1024, min(int(maxsize), 65536)),
            )
        except Exception as e:  # CompactedError -> 410 Gone
            raise APIError(410, "Expired", str(e))

    # -- bindings (the scheduler's commit path) ------------------------

    def bind(self, namespace: str, binding: dict) -> dict:
        """POST /bindings: set pod.spec.nodeName iff currently empty.

        Reference: BindingREST.Create -> assignPod -> GuaranteedUpdate
        with the emptiness guard (pkg/registry/pod/etcd/etcd.go:123-181).
        """
        pod_name = binding.get("metadata", {}).get("name", "")
        target = binding.get("target", {})
        node_name = target.get("name", "")
        if not pod_name or not node_name:
            raise _bad_request("binding requires metadata.name and target.name")
        if target.get("kind", "") not in ("", "Node", "Minion"):
            raise _bad_request(f"cannot bind to {target.get('kind')!r}")
        key = RESOURCES["pods"].key(namespace or "default", pod_name)

        def assign(cur: dict) -> dict:
            spec = cur.setdefault("spec", {})
            if spec.get("nodeName"):
                raise _conflict(
                    f'pod "{pod_name}" is already assigned to node '
                    f'"{spec["nodeName"]}"'
                )
            spec["nodeName"] = node_name
            return cur

        try:
            self.store.atomic_update(key, assign)
        except NotFoundError:
            raise _not_found("pods", pod_name)
        except ConflictError as e:
            # The already-assigned guard raises inside atomic_update
            # and surfaces as 409 (the caller retries the pod).
            raise _conflict(str(e))
        return {
            "kind": "Status",
            "apiVersion": "v1",
            "status": "Success",
            "code": 201,
        }

    # -- bulk object verbs (the write fast path) -----------------------

    #: Resources whose create/delete carry side effects (allocators,
    #: finalizer phases) — bulk falls back to the per-item verbs there.
    _BULK_SLOW = frozenset({"services", "namespaces"})

    def create_bulk(
        self, resource: str, namespace: str, items, copy: bool = True
    ) -> list:
        """POST {resource}:bulk — create N objects through ONE store
        batch (one lock hold, one WAL append, one group-commit fsync;
        KVStore.create_many). Per-item Status results in input order;
        failures never abort the rest (pods are independent objects —
        the atomic path is bind_bulk(atomic=True), not creation).
        Watch events land in version order matching the input order.

        copy=False trusts the items to be PRIVATE dicts (the HTTP
        tier's just-parsed body); in-process callers keep the copy."""
        info = self._info(resource)
        if isinstance(items, dict):
            items = items.get("items", [])
        out: List[Optional[dict]] = [None] * len(items)
        if info.name in self._BULK_SLOW or self.admission is not None:
            # Admission is check-then-act against CURRENT usage: a
            # batched admit-everything-then-commit would let one
            # request blow a hard quota/limit by up to the batch size.
            # With a chain configured, each item takes the full
            # admit->commit->bookkeep cycle (correctness over the
            # group-commit fast path).
            for i, obj in enumerate(items):
                try:
                    created = self.create(resource, namespace, obj)
                    out[i] = self._created_status(created)
                except APIError as e:
                    out[i] = e.to_status()
                except Exception as e:
                    out[i] = _invalid(f"{type(e).__name__}: {e}").to_status()
            return out
        entries = []
        entry_idx = []
        with self._write_guard():
            for i, obj in enumerate(items):
                try:
                    ns, name = self._default_create_meta(
                        info, namespace, obj
                    )
                    self._admit("CREATE", info, ns, name, obj)
                    self._validate_fast(info, obj)
                except APIError as e:
                    out[i] = e.to_status()
                    continue
                except Exception as e:
                    # Per-item contract: a malformed object (non-
                    # numeric priority, non-string label value, ...)
                    # that slips past the validator's field checks
                    # must fail ITS slot, never abort the batch.
                    out[i] = _invalid(f"{type(e).__name__}: {e}").to_status()
                    continue
                entries.append((info.key(ns, name), obj, info.ttl))
                entry_idx.append(i)
            if entries:
                results = self.store.create_many(entries, copy=copy)
                for i, res in zip(entry_idx, results):
                    if isinstance(res, AlreadyExistsError):
                        name = items[i].get("metadata", {}).get("name", "")
                        out[i] = _conflict(
                            f'{info.name} "{name}" already exists'
                        ).to_status()
                    elif isinstance(res, Exception):
                        out[i] = APIError(
                            500, "InternalError", str(res)
                        ).to_status()
                    else:
                        out[i] = self._created_status(res)
                        self._commit(
                            "CREATE", info,
                            res.get("metadata", {}).get("namespace", ""),
                            res.get("metadata", {}).get("name", ""), res,
                        )
        return out

    @staticmethod
    def _created_status(obj: dict) -> dict:
        meta = obj.get("metadata", {})
        return {
            "kind": "Status",
            "apiVersion": "v1",
            "status": "Success",
            "code": 201,
            "details": {
                "name": meta.get("name", ""),
                "resourceVersion": meta.get("resourceVersion", ""),
            },
        }

    def _default_create_meta(
        self, info: ResourceInfo, namespace: str, obj: dict
    ) -> Tuple[str, str]:
        """The create() defaulting pass (namespace/name/kind/uid/
        creationTimestamp), shared by the single and bulk paths."""
        meta = obj.setdefault("metadata", {})
        if info.namespaced:
            ns = meta.get("namespace") or namespace or "default"
            meta["namespace"] = ns
            if namespace and ns != namespace:
                raise _bad_request(
                    f"namespace mismatch: body {ns!r} vs url {namespace!r}"
                )
        else:
            meta.pop("namespace", None)
            ns = ""
        if not meta.get("name") and meta.get("generateName"):
            meta["name"] = self._gen_name(meta["generateName"])
        if not meta.get("name"):
            raise _invalid("metadata.name: required")
        obj.setdefault("kind", info.kind)
        obj.setdefault("apiVersion", "v1")
        if obj["kind"] != info.kind:
            raise _bad_request(
                f"kind {obj['kind']!r} does not match {info.kind!r}"
            )
        meta["uid"] = new_uid()
        meta["creationTimestamp"] = now_iso()
        meta.pop("resourceVersion", None)
        return ns, meta["name"]

    def update_bulk(
        self, resource: str, namespace: str, items, copy: bool = True
    ) -> list:
        """POST {resource}:bulkupdate — replace N objects through one
        store batch (atomic_update_many: one lock hold, one WAL append,
        one fsync). Each item keeps update()'s semantics: CAS when the
        body carries metadata.resourceVersion, last-write-wins when
        not; uid/creationTimestamp carry over from the stored object.

        copy=False trusts the items to be PRIVATE dicts (the HTTP
        tier's parsed body): the store then skips its defensive
        per-item round-trip copies — the dominant bulk-update cost."""
        info = self._info(resource)
        if isinstance(items, dict):
            items = items.get("items", [])
        if info.name in self._BULK_SLOW or self.admission is not None:
            # Same check-then-act concern as create_bulk: quota usage
            # deltas must be observed item by item under the guard.
            out = []
            for obj in items:
                try:
                    name = obj.get("metadata", {}).get("name", "")
                    self.update(resource, namespace, name, obj)
                    out.append(
                        {"kind": "Status", "apiVersion": "v1",
                         "status": "Success", "code": 200}
                    )
                except APIError as e:
                    out.append(e.to_status())
                except Exception as e:
                    out.append(
                        _invalid(f"{type(e).__name__}: {e}").to_status()
                    )
            return out
        out = [None] * len(items)
        ops = []
        op_idx = []
        with self._write_guard():
            for i, obj in enumerate(items):
                ns = self._ns(info, namespace)
                try:
                    meta = obj.setdefault("metadata", {})
                    name = meta.get("name", "")
                    if info.namespaced:
                        meta.setdefault("namespace", ns)
                    if not name:
                        out[i] = _invalid(
                            "metadata.name: required"
                        ).to_status()
                        continue
                    expected = None
                    if meta.get("resourceVersion"):
                        try:
                            expected = int(meta["resourceVersion"])
                        except ValueError:
                            out[i] = _bad_request(
                                f"invalid resourceVersion "
                                f"{meta['resourceVersion']!r}"
                            ).to_status()
                            continue
                    self._admit("UPDATE", info, ns, name, obj)
                    self._validate_fast(info, obj)
                except APIError as e:
                    out[i] = e.to_status()
                    continue
                except Exception as e:
                    # Per-item contract: a malformed item (non-dict,
                    # string metadata, ...) fails ITS slot, never the
                    # batch.
                    out[i] = _invalid(f"{type(e).__name__}: {e}").to_status()
                    continue

                def apply(cur, _obj=obj, _expected=expected):
                    if _expected is not None:
                        cur_v = int(
                            cur.get("metadata", {})
                            .get("resourceVersion", "0") or "0"
                        )
                        if cur_v != _expected:
                            raise ConflictError(
                                f"version {_expected} != current {cur_v}"
                            )
                    m_cur = cur.get("metadata", {})
                    m = _obj.setdefault("metadata", {})
                    m["uid"] = m_cur.get("uid", "")
                    m["creationTimestamp"] = m_cur.get(
                        "creationTimestamp", ""
                    )
                    return _obj

                ops.append((info.key(ns, name), apply))
                op_idx.append(i)
            if ops:
                results = self.store.atomic_update_many(
                    ops, copy=copy, copy_results=False
                )
                for i, res in zip(op_idx, results):
                    name = items[i].get("metadata", {}).get("name", "")
                    if isinstance(res, NotFoundError):
                        out[i] = _not_found(info.name, name).to_status()
                    elif isinstance(res, ConflictError):
                        out[i] = _conflict(str(res)).to_status()
                    elif isinstance(res, Exception):
                        out[i] = APIError(
                            500, "InternalError", str(res)
                        ).to_status()
                    else:
                        out[i] = {
                            "kind": "Status", "apiVersion": "v1",
                            "status": "Success", "code": 200,
                            "details": {
                                "name": name,
                                "resourceVersion": res.get("metadata", {})
                                .get("resourceVersion", ""),
                            },
                        }
                        self._commit("UPDATE", info, ns, name, res)
        return out

    def delete_bulk(self, resource: str, namespace: str, names) -> list:
        """POST {resource}:bulkdelete — immediate delete of N objects
        through one store batch (delete_many: one lock hold, one WAL
        append, one fsync). Graceful pod termination is a per-item
        concern; this is the churn-drain path (the reference analog is
        a DeleteCollection)."""
        info = self._info(resource)
        if isinstance(names, dict):
            names = names.get("names", [])
        if info.name in self._BULK_SLOW or self.admission is not None:
            # Per-item when an admission chain is configured so usage
            # bookkeeping (quota release) observes each delete.
            out = []
            for name in names:
                try:
                    self.delete(resource, namespace, name)
                    out.append(
                        {"kind": "Status", "apiVersion": "v1",
                         "status": "Success", "code": 200}
                    )
                except APIError as e:
                    out.append(e.to_status())
            return out
        ns = self._ns(info, namespace)
        out = [None] * len(names)
        keys = []
        key_idx = []
        with self._write_guard():
            for i, name in enumerate(names):
                try:
                    self._admit("DELETE", info, ns, name, None)
                except APIError as e:
                    out[i] = e.to_status()
                    continue
                keys.append(info.key(ns, name))
                key_idx.append(i)
            if keys:
                results = self.store.delete_many(keys)
                for i, res in zip(key_idx, results):
                    if isinstance(res, NotFoundError):
                        out[i] = _not_found(info.name, names[i]).to_status()
                    elif isinstance(res, Exception):
                        out[i] = APIError(
                            500, "InternalError", str(res)
                        ).to_status()
                    else:
                        out[i] = {
                            "kind": "Status", "apiVersion": "v1",
                            "status": "Success", "code": 200,
                        }
                        self._commit("DELETE", info, ns, names[i], None)
        return out

    def create_events_bulk(self, namespace: str, items) -> list:
        """Write many Events in one call — the event broadcaster's
        batched sink. No reference analog: one POST per event
        (pkg/client/record/event.go recordToSink) is viable at the
        reference's 15 binds/s but becomes the control plane's largest
        per-pod cost at 1k+ binds/s. Per-item results; each event still
        takes the normal create path (validation, TTL, watch fan-out)."""
        if isinstance(items, dict):
            items = items.get("items", [])
        results = []
        for ev in items:
            ns = ev.get("metadata", {}).get("namespace") or namespace or "default"
            try:
                self.create("events", ns, ev)
                results.append(
                    {
                        "kind": "Status",
                        "apiVersion": "v1",
                        "status": "Success",
                        "code": 201,
                    }
                )
            except APIError as e:
                results.append(e.to_status())
        return results

    def bind_bulk(
        self, namespace: str, bindings, atomic: bool = False
    ) -> list:
        """Commit many bindings in one call (no reference analog — this
        is the batch-solver commit path: one request for a whole solved
        backlog instead of one per pod). The whole batch runs as ONE
        store apply (atomic_update_many): per-binding lock acquisitions
        would queue the scheduler behind every kubelet status writer
        once per pod — at 1000 nodes that convoy, not the solve, was
        the bind-rate ceiling. Each binding keeps the same guarded
        emptiness check; per-item Status results are returned.

        atomic=True (the gang-commit mode) makes the batch all-or-
        nothing: the first conflict/invalid binding rejects EVERY
        binding in the batch and commits none — the store stages all
        writes and only publishes when every guard passes, so no pod is
        ever observed bound and then rolled back. The failing item
        carries its real error; the rest answer 409 Aborted."""
        from kubernetes_tpu.store import AbortedError, NotFoundError

        if isinstance(bindings, dict):
            atomic = bool(bindings.get("atomic", atomic))
            bindings = bindings.get("bindings", [])
        aborted = APIError(
            409, "Aborted", "atomic bind batch aborted; nothing applied"
        ).to_status()
        out: List[Optional[dict]] = [None] * len(bindings)
        ops = []
        op_idx = []
        for i, binding in enumerate(bindings):
            pod_name = binding.get("metadata", {}).get("name", "")
            target = binding.get("target", {})
            node_name = target.get("name", "")
            if not pod_name or not node_name:
                out[i] = _bad_request(
                    "binding requires metadata.name and target.name"
                ).to_status()
                continue
            if target.get("kind", "") not in ("", "Node", "Minion"):
                out[i] = _bad_request(
                    f"cannot bind to {target.get('kind')!r}"
                ).to_status()
                continue
            key = RESOURCES["pods"].key(namespace or "default", pod_name)

            def assign(cur: dict, _node=node_name, _pod=pod_name) -> dict:
                spec = cur.setdefault("spec", {})
                if spec.get("nodeName"):
                    raise _conflict(
                        f'pod "{_pod}" is already assigned to node '
                        f'"{spec["nodeName"]}"'
                    )
                spec["nodeName"] = _node
                return cur

            ops.append((key, assign))
            op_idx.append(i)
        if atomic and any(o is not None for o in out):
            # A malformed binding rejects the whole atomic batch before
            # any store work (reject-all on first invalid item).
            return [o if o is not None else aborted for o in out]
        if ops:
            # copy_results=False: only per-item status is inspected;
            # a result copy per binding would re-copy the whole solved
            # backlog on every bulk commit.
            results = self.store.atomic_update_many(
                ops, atomic=atomic, copy_results=False
            )
            for i, res in zip(op_idx, results):
                if isinstance(res, APIError):
                    out[i] = res.to_status()
                elif isinstance(res, AbortedError):
                    out[i] = aborted
                elif isinstance(res, NotFoundError):
                    name = bindings[i].get("metadata", {}).get("name", "")
                    out[i] = _not_found("pods", name).to_status()
                elif isinstance(res, Exception):
                    out[i] = APIError(
                        500, "InternalError", str(res)
                    ).to_status()
                else:
                    out[i] = {
                        "kind": "Status",
                        "apiVersion": "v1",
                        "status": "Success",
                        "code": 201,
                    }
        return out
