"""API server: REST + watch over the versioned store.

Reference: pkg/apiserver/ + pkg/master/ + pkg/registry/. The core
(`APIServer`) is transport-independent; `httpserver` exposes it over
HTTP with chunked watch streams. Components in the same process can
use the core directly (the reference's cmd/integration runs everything
in one process the same way).
"""

from kubernetes_tpu.server.api import APIError, APIServer
from kubernetes_tpu.server.registry import RESOURCES, ResourceInfo

__all__ = ["APIServer", "APIError", "RESOURCES", "ResourceInfo"]
