"""Reconciliation controllers.

Reference: pkg/controller/ (replication), pkg/service/ (endpoints),
pkg/cloudprovider/nodecontroller/ (node lifecycle), aggregated by
cmd/kube-controller-manager.
"""

from kubernetes_tpu.controllers.replication import ReplicationManager
from kubernetes_tpu.controllers.endpoints import EndpointsController
from kubernetes_tpu.controllers.nodelifecycle import NodeLifecycleController
from kubernetes_tpu.controllers.manager import ControllerManager

__all__ = [
    "ReplicationManager",
    "EndpointsController",
    "NodeLifecycleController",
    "ControllerManager",
]
