"""EndpointsController: joins services and ready pods into Endpoints.

Reference: pkg/service/endpoints_controller.go:59,255 — for each
service, list pods matching its selector, keep the ready ones with pod
IPs, and write an Endpoints object mirroring the service's ports.
"""

from __future__ import annotations

import logging
import threading
from typing import List, Optional

from kubernetes_tpu.client.cache import Informer
from kubernetes_tpu.models import labels as labelpkg
from kubernetes_tpu.models import serde
from kubernetes_tpu.models.objects import (
    EndpointAddress,
    EndpointPort,
    Endpoints,
    EndpointSubset,
    Pod,
    Service,
)
from kubernetes_tpu.server.api import APIError

_LOG = logging.getLogger("kubernetes_tpu.controllers.endpoints")


def _decode_pod(wire: dict) -> Pod:
    return serde.from_wire(Pod, wire)


def _decode_service(wire: dict) -> Service:
    return serde.from_wire(Service, wire)


def _pod_ready(pod: Pod) -> bool:
    if pod.status.phase != "Running" or not pod.status.pod_ip:
        return False
    for c in pod.status.conditions:
        if c.type == "Ready":
            return c.status == "True"
    return False


class EndpointsController:
    def __init__(self, client, sync_period: float = 3.0):
        self.client = client
        self.sync_period = sync_period
        self._dirty = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        mark = lambda o: self._dirty.set()  # noqa: E731
        self.services = Informer(
            client, "services", decode=_decode_service,
            on_add=mark, on_update=mark, on_delete=mark,
        )
        self.pods = Informer(
            client, "pods", decode=_decode_pod,
            on_add=mark, on_update=mark, on_delete=mark,
        )
        # Endpoints cache for orphan GC: the per-sync full LIST of
        # endpoints was the controller's remaining steady-state read
        # against the API plane (wire dicts are enough — GC only needs
        # keys).
        self.endpoints = Informer(client, "endpoints")

    def start(self) -> "EndpointsController":
        self.services.start()
        self.pods.start()
        self.endpoints.start()
        self.services.wait_for_sync()
        self.pods.wait_for_sync()
        self.endpoints.wait_for_sync()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._dirty.set()
        self.services.stop()
        self.pods.stop()
        self.endpoints.stop()
        if self._thread:
            self._thread.join(timeout=3)

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._dirty.wait(timeout=self.sync_period)
            self._dirty.clear()
            if self._stop.is_set():
                return
            try:
                self.sync_all()
            except Exception:
                _LOG.exception("endpoints sync pass failed")

    def sync_all(self) -> None:
        services = self.services.store.list()
        for svc in services:
            try:
                self.sync_service(svc)
            except Exception:
                _LOG.exception(
                    "endpoints sync for service %s/%s failed",
                    svc.metadata.namespace, svc.metadata.name,
                )
        self._gc_orphans(services)

    def _gc_orphans(self, services: List[Service]) -> None:
        """Endpoints whose service is gone are garbage-collected
        (reference: endpoints_controller.go removes them)."""
        live = {f"{s.metadata.namespace}/{s.metadata.name}" for s in services}
        # Informer-fed: no per-sync endpoints LIST. The undecoded cache
        # mixes typed objects (reflector list) and wire dicts (watch
        # events); GC only needs the key, so read both shapes.
        for ep in self.endpoints.store.list():
            if isinstance(ep, dict):
                meta = ep.get("metadata", {})
                ns = meta.get("namespace", "")
                name = meta.get("name", "")
            else:
                ns, name = ep.metadata.namespace, ep.metadata.name
            if f"{ns}/{name}" not in live:
                try:
                    self.client.delete(
                        "endpoints", name, namespace=ns or "default"
                    )
                except APIError:
                    pass

    @staticmethod
    def _resolve_target_port(service_port, pod: Pod) -> int:
        """findPort (reference: pkg/util/findPort as used by the
        endpoints controller): int targetPort used directly; named
        targetPort resolved against the pod's container ports; empty
        falls back to the service port."""
        tp = service_port.target_port
        if isinstance(tp, int) and tp:
            return tp
        if isinstance(tp, str) and tp:
            for c in pod.spec.containers:
                for p in c.ports:
                    if p.name == tp:
                        return p.container_port
        return service_port.port

    def sync_service(self, svc: Service) -> None:
        if not svc.spec.selector:
            return  # headless/external services manage their own endpoints
        sel = labelpkg.selector_from_set(svc.spec.selector)
        # Named targetPorts resolve PER POD (two pods can expose the
        # same port name on different container ports), so addresses
        # group by their resolved port tuple — one subset per distinct
        # tuple, the reference's endpoints.RepackSubsets shape
        # (endpoints_controller.go:255 + pkg/api/endpoints/util.go).
        groups: dict = {}
        for pod in self.pods.store.list():
            if pod.metadata.namespace != svc.metadata.namespace:
                continue
            if not sel.matches(pod.metadata.labels):
                continue
            if not _pod_ready(pod):
                continue
            ports = tuple(
                (p.name, self._resolve_target_port(p, pod), p.protocol)
                for p in svc.spec.ports
            )
            groups.setdefault(ports, []).append(
                EndpointAddress(
                    ip=pod.status.pod_ip,
                    target_ref={
                        "kind": "Pod",
                        "name": pod.metadata.name,
                        "namespace": pod.metadata.namespace,
                        "uid": pod.metadata.uid,
                    },
                )
            )
        subsets = []
        for ports, addresses in sorted(groups.items()):
            addresses.sort(key=lambda a: (a.ip, (a.target_ref or {}).get("uid", "")))
            subsets.append(
                EndpointSubset(
                    addresses=addresses,
                    ports=[
                        EndpointPort(name=n, port=num, protocol=proto)
                        for (n, num, proto) in ports
                    ],
                )
            )
        ep = Endpoints()
        ep.metadata.name = svc.metadata.name
        ep.metadata.namespace = svc.metadata.namespace
        ep.subsets = subsets
        ns = svc.metadata.namespace or "default"
        try:
            current = self.client.get("endpoints", svc.metadata.name, namespace=ns)
            if serde.to_wire(current.subsets) == serde.to_wire(ep.subsets):
                return  # no change
            current.subsets = ep.subsets
            self.client.update("endpoints", current, namespace=ns)
        except APIError as e:
            if e.code == 404:
                try:
                    self.client.create("endpoints", ep, namespace=ns)
                except APIError:
                    pass
