"""ServiceAccount + token controllers.

Reference: pkg/serviceaccount/serviceaccounts_controller.go (ensure a
'default' ServiceAccount exists in every active namespace) and
tokens_controller.go (mint a signed API token Secret for every
ServiceAccount and reference it from sa.secrets).

Token format: HMAC-SHA256 JWT from
kubernetes_tpu.server.auth.ServiceAccountTokenManager (the reference
signs RS256; see auth.py module docstring for the deviation note).
"""

from __future__ import annotations

import base64
import logging
import threading
from typing import Optional

from kubernetes_tpu.models.objects import ObjectReference
from kubernetes_tpu.server.api import APIError
from kubernetes_tpu.server.auth import ServiceAccountTokenManager
from kubernetes_tpu.utils import metrics

DEFAULT_SERVICE_ACCOUNT = "default"
SECRET_TYPE_SA_TOKEN = "kubernetes.io/service-account-token"

_LOG = logging.getLogger("kubernetes_tpu.controllers.serviceaccounts")

_SYNCS = metrics.DEFAULT.counter(
    "serviceaccount_controller_syncs_total", "SA sync passes", ("result",)
)


class ServiceAccountsController:
    """Ensure every Active namespace has a 'default' ServiceAccount."""

    def __init__(self, client, sync_period: float = 5.0):
        self.client = client
        self.sync_period = sync_period
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "ServiceAccountsController":
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=3)

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.sync_once()
            except Exception:
                _LOG.exception("serviceaccount sync pass failed")
                _SYNCS.inc(result="error")
            self._stop.wait(self.sync_period)

    def sync_once(self) -> int:
        created = 0
        namespaces, _ = self.client.list("namespaces")
        for ns in namespaces:
            if ns.status.phase != "Active":
                continue
            name = ns.metadata.name
            try:
                self.client.get(
                    "serviceaccounts", DEFAULT_SERVICE_ACCOUNT, namespace=name
                )
            except APIError:
                try:
                    self.client.create(
                        "serviceaccounts",
                        {
                            "kind": "ServiceAccount",
                            "metadata": {
                                "name": DEFAULT_SERVICE_ACCOUNT,
                                "namespace": name,
                            },
                        },
                        namespace=name,
                    )
                    created += 1
                    _SYNCS.inc(result="created")
                except APIError:
                    pass  # racing creator / terminating namespace
        return created


class TokenController:
    """Mint an API token Secret for ServiceAccounts that lack one."""

    def __init__(
        self,
        client,
        token_manager: ServiceAccountTokenManager,
        sync_period: float = 5.0,
    ):
        self.client = client
        self.tokens = token_manager
        self.sync_period = sync_period
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "TokenController":
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=3)

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.sync_once()
            except Exception:
                _LOG.exception("serviceaccount token sync pass failed")
                _SYNCS.inc(result="error")
            self._stop.wait(self.sync_period)

    def sync_once(self) -> int:
        minted = 0
        accounts, _ = self.client.list("serviceaccounts")
        for sa in accounts:
            if any(
                ref.name.startswith(f"{sa.metadata.name}-token")
                for ref in sa.secrets
            ):
                continue
            if self._mint(sa):
                minted += 1
        return minted

    def _mint(self, sa) -> bool:
        ns = sa.metadata.namespace
        secret_name = f"{sa.metadata.name}-token"
        token = self.tokens.mint(
            ns, sa.metadata.name, uid=sa.metadata.uid, secret_name=secret_name
        )
        secret = {
            "kind": "Secret",
            "metadata": {
                "name": secret_name,
                "namespace": ns,
                "annotations": {
                    "kubernetes.io/service-account.name": sa.metadata.name,
                    "kubernetes.io/service-account.uid": sa.metadata.uid,
                },
            },
            "type": SECRET_TYPE_SA_TOKEN,
            "data": {"token": base64.b64encode(token.encode()).decode()},
        }
        try:
            self.client.create("secrets", secret, namespace=ns)
        except APIError as e:
            if e.code != 409:  # already minted by a racing sync
                return False
        sa.secrets.append(
            ObjectReference(kind="Secret", namespace=ns, name=secret_name)
        )
        try:
            self.client.update("serviceaccounts", sa, namespace=ns)
            _SYNCS.inc(result="minted")
            return True
        except APIError:
            return False
