"""ResourceQuotaManager: periodic full recalculation of quota usage.

Reference: pkg/resourcequota/resource_quota_manager.go — the admission
plugin keeps status.used current incrementally; this controller is the
level-triggered backstop that recomputes observed usage from scratch
every sync period and fixes any drift (missed deletes, direct store
writes, controller restarts).
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, Optional

from kubernetes_tpu.models.quantity import Quantity
from kubernetes_tpu.server.admission import COUNTED_RESOURCES
from kubernetes_tpu.server.api import APIError
from kubernetes_tpu.utils import metrics

_LOG = logging.getLogger("kubernetes_tpu.controllers.resourcequota")

_SYNCS = metrics.DEFAULT.counter(
    "resource_quota_controller_syncs_total", "quota sync passes", ("result",)
)


class ResourceQuotaManager:
    def __init__(self, client, sync_period: float = 10.0):
        self.client = client
        self.sync_period = sync_period
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "ResourceQuotaManager":
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=3)

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.sync_once()
                _SYNCS.inc(result="ok")
            except Exception:
                _LOG.exception("resourcequota sync pass failed")
                _SYNCS.inc(result="error")
            self._stop.wait(self.sync_period)

    def sync_once(self) -> int:
        """Recompute status for every quota; returns quotas updated."""
        updated = 0
        quotas, _ = self.client.list("resourcequotas")
        for quota in quotas:
            hard = quota.spec.hard or {}
            if not hard:
                continue
            ns = quota.metadata.namespace
            used = self._compute_usage(ns, hard)
            old_used = {k: str(v) for k, v in (quota.status.used or {}).items()}
            if used == old_used:
                continue
            quota.status.hard = dict(hard)
            quota.status.used = {k: Quantity.from_string(v) for k, v in used.items()}
            try:
                self.client.update_status("resourcequotas", quota, namespace=ns)
                updated += 1
            except APIError:
                pass  # CAS loss; next period recomputes
        return updated

    def _compute_usage(self, namespace: str, hard) -> Dict[str, str]:
        used: Dict[str, str] = {}
        pods = None
        for key in hard:
            if key in COUNTED_RESOURCES:
                items, _ = self.client.list(key, namespace=namespace)
                used[key] = str(len(items))
            elif key in ("cpu", "memory"):
                if pods is None:
                    pods, _ = self.client.list("pods", namespace=namespace)
                total = 0
                for pod in pods:
                    for c in pod.spec.containers:
                        q = c.resources.limits.get(key) or c.resources.requests.get(
                            key
                        )
                        if q is not None:
                            total += q.milli_value()
                used[key] = str(Quantity.from_milli(total))
        return used
