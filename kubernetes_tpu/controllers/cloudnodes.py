"""CloudNodeController: sync Node objects with the cloud's instances.

Reference: pkg/cloudprovider/nodecontroller/nodecontroller.go:99-180 —
with --sync_nodes the controller registers a Node per cloud instance
and deletes Nodes whose instance disappeared; zone/instance-type
surface as node labels. TPU analog: the instance list is the slice's
host inventory (cloudprovider/tpu.py), so scaling or reconfiguring the
slice shows up as nodes joining/leaving the cluster.
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

from kubernetes_tpu.cloudprovider.interface import CloudProvider
from kubernetes_tpu.models.objects import Node, NodeCondition
from kubernetes_tpu.models.quantity import parse_quantity
from kubernetes_tpu.server.api import APIError
from kubernetes_tpu.utils import metrics

LABEL_INSTANCE_TYPE = "node.kubernetes-tpu.io/instance-type"
LABEL_ZONE = "failure-domain.kubernetes-tpu.io/zone"
LABEL_REGION = "failure-domain.kubernetes-tpu.io/region"
LABEL_MANAGED = "node.kubernetes-tpu.io/managed-by"

_LOG = logging.getLogger("kubernetes_tpu.controllers.cloudnodes")

_SYNCS = metrics.DEFAULT.counter(
    "cloud_node_syncs_total", "cloud node sync actions", ("action",)
)


class CloudNodeController:
    def __init__(
        self,
        client,
        provider: CloudProvider,
        sync_period: float = 5.0,
        default_cpu: str = "4",
        default_memory: str = "8Gi",
        max_pods: int = 110,
    ):
        self.client = client
        self.provider = provider
        self.sync_period = sync_period
        self.default_cpu = default_cpu
        self.default_memory = default_memory
        self.max_pods = max_pods
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "CloudNodeController":
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=3)

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.sync_once()
            except Exception:
                _LOG.exception("cloud-node sync pass failed")
                _SYNCS.inc(action="error")
            self._stop.wait(self.sync_period)

    def sync_once(self) -> int:
        """Register missing nodes, delete departed ones. Returns the
        number of changes made."""
        instances = self.provider.instances()
        if instances is None:
            return 0
        changed = 0
        want = {}
        for inst in instances:
            want[inst.name] = inst
        have, _ = self.client.list("nodes")
        have_names = set()
        for node in have:
            have_names.add(node.metadata.name)
            if node.metadata.name not in want:
                # Only reap nodes THIS controller registered; manually
                # registered nodes (self-registering kubelets) are not
                # the cloud's to delete.
                if node.metadata.labels.get(LABEL_MANAGED) == "cloud":
                    try:
                        self.client.delete("nodes", node.metadata.name)
                        changed += 1
                        _SYNCS.inc(action="delete")
                    except APIError:
                        pass
        for name, inst in want.items():
            if name in have_names:
                continue
            if self._register(inst):
                changed += 1
                _SYNCS.inc(action="register")
        return changed

    def _register(self, inst) -> bool:
        node = Node()
        node.metadata.name = inst.name
        labels = dict(inst.labels_dict())
        labels[LABEL_MANAGED] = "cloud"
        if inst.instance_type:
            labels[LABEL_INSTANCE_TYPE] = inst.instance_type
        zone = self.provider.zone_of(inst.name)
        if zone is not None:
            labels[LABEL_ZONE] = zone.failure_domain.replace("/", "_")
            labels[LABEL_REGION] = zone.region
        node.metadata.labels = labels
        node.status.capacity = {
            "cpu": parse_quantity(self.default_cpu),
            "memory": parse_quantity(self.default_memory),
            "pods": parse_quantity(str(self.max_pods)),
        }
        # Registered without a heartbeat: Ready=Unknown until a kubelet
        # on that host reports in (nodecontroller.go registers with
        # status unknown similarly).
        node.status.conditions = [
            NodeCondition(type="Ready", status="Unknown", reason="CloudRegistered")
        ]
        try:
            self.client.create("nodes", node)
            return True
        except APIError as e:
            if e.code != 409:  # 409: a kubelet self-registered first — fine
                _SYNCS.inc(action="error")
            return False
