"""RouteController: reconciles provider routes against node podCIDRs.

Reference: pkg/cloudprovider/routecontroller/routecontroller.go — every
node with a spec.podCIDR gets a provider route sending that CIDR to the
node; routes whose node (or CIDR) is gone are deleted. The TPU
provider's base connectivity is the ICI ring discovered from the fabric
(cloudprovider/tpu.py routes()); managed pod-CIDR routes layer on top.
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

from kubernetes_tpu.client.cache import Informer
from kubernetes_tpu.models import serde
from kubernetes_tpu.models.objects import Node
from kubernetes_tpu.utils import metrics

_LOG = logging.getLogger("kubernetes_tpu.controllers.routes")

_SYNCS = metrics.DEFAULT.counter(
    "route_syncs_total", "route sync outcomes", ("action",)
)


def _decode_node(wire: dict) -> Node:
    return serde.from_wire(Node, wire)


def route_name(node_name: str) -> str:
    return f"podcidr-{node_name}"


class RouteController:
    def __init__(self, client, provider, sync_period: float = 1.0):
        self.client = client
        self.provider = provider
        self.sync_period = sync_period
        self._dirty = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        mark = lambda o: self._dirty.set()  # noqa: E731
        self.nodes = Informer(
            client, "nodes", decode=_decode_node,
            on_add=mark, on_update=mark, on_delete=mark,
        )

    def start(self) -> "RouteController":
        if self.provider.routes() is None:
            raise ValueError("cloud provider has no routes surface")
        self.nodes.start()
        self.nodes.wait_for_sync()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._dirty.set()
        self.nodes.stop()
        if self._thread:
            self._thread.join(timeout=2)

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._dirty.wait(self.sync_period)
            self._dirty.clear()
            if self._stop.is_set():
                return
            try:
                self.sync()
                _SYNCS.inc(action="ok")
            except Exception:
                # Crash containment, but visibly (cloudnodes pattern).
                _LOG.exception("route sync pass failed")
                _SYNCS.inc(action="error")

    def sync(self) -> None:
        nodes = {n.metadata.name: n for n in self.nodes.store.list()}
        existing = {r.name: r for r in (self.provider.routes() or [])}
        # Ensure a route per node with a podCIDR.
        for name, node in nodes.items():
            cidr = node.spec.pod_cidr
            if not cidr:
                continue
            rname = route_name(name)
            cur = existing.get(rname)
            if cur is not None and cur.destination_cidr == cidr:
                continue
            if cur is not None:
                self.provider.delete_route(rname)  # CIDR moved
            self.provider.create_route(rname, name, cidr)
        # Delete managed routes whose node is gone. Only routes this
        # controller created (podcidr- prefix) are touched: the
        # provider's base fabric routes (ICI ring) are not ours.
        for rname, route in existing.items():
            if not rname.startswith("podcidr-"):
                continue
            node = nodes.get(route.target_instance)
            if node is None or not node.spec.pod_cidr:
                self.provider.delete_route(rname)
