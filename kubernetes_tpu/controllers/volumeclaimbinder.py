"""PersistentVolumeClaimBinder: match Pending claims to Available
volumes.

Reference: pkg/volumeclaimbinder/persistent_volume_claim_binder.go —
smallest-sufficient-volume matching on capacity + access modes, bind by
cross-referencing pv.spec.claimRef <-> pvc.spec.volumeName, release on
claim deletion honoring the reclaim policy (Retain keeps the volume
Released; Recycle returns it to Available).
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

from kubernetes_tpu.models.objects import ObjectReference
from kubernetes_tpu.server.api import APIError
from kubernetes_tpu.utils import metrics

_LOG = logging.getLogger("kubernetes_tpu.controllers.volumeclaimbinder")

_SYNCS = metrics.DEFAULT.counter(
    "pv_claim_binder_syncs_total", "PV claim binder passes", ("result",)
)


def _storage_milli(resource_list) -> int:
    q = (resource_list or {}).get("storage")
    return q.milli_value() if q is not None else 0


class PersistentVolumeClaimBinder:
    def __init__(self, client, sync_period: float = 2.0):
        self.client = client
        self.sync_period = sync_period
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "PersistentVolumeClaimBinder":
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=3)

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.sync_once()
            except Exception:
                _LOG.exception("claim binder sync pass failed")
                _SYNCS.inc(result="error")
            self._stop.wait(self.sync_period)

    def sync_once(self) -> int:
        """Bind pending claims, release orphaned volumes; returns the
        number of bindings made."""
        volumes, _ = self.client.list("persistentvolumes")
        claims, _ = self.client.list("persistentvolumeclaims")
        bound = 0

        # Phase transitions for fresh volumes. Status writes bump the
        # resourceVersion, so re-list before the CAS'd bind updates.
        transitioned = False
        for pv in volumes:
            if pv.status.phase == "Pending":
                pv.status.phase = "Available"
                self._put_pv_status(pv)
                transitioned = True
        if transitioned:
            volumes, _ = self.client.list("persistentvolumes")

        # Release volumes whose claim vanished — including a claim
        # deleted and RECREATED under the same name (uid mismatch): the
        # reservation belonged to the old claim, never the new one.
        claim_uids = {
            (c.metadata.namespace, c.metadata.name): c.metadata.uid for c in claims
        }
        for pv in volumes:
            ref = pv.spec.claim_ref
            if ref is None:
                continue
            current_uid = claim_uids.get((ref.namespace, ref.name))
            if current_uid is not None and (not ref.uid or ref.uid == current_uid):
                continue  # the claim it references still exists
            if pv.status.phase == "Bound":
                self._release(pv)
            elif pv.status.phase != "Released":
                # Reserved (claimRef set) but never fully bound, and
                # the claim is gone: just return it to the pool.
                # Released volumes stay Released — Retain semantics;
                # re-pooling them would hand old data to a new tenant.
                self._rollback(pv.metadata.name)

        # Bind pending claims: smallest sufficient Available volume.
        available = [
            pv
            for pv in volumes
            if pv.status.phase in ("Available", "Pending")
            and pv.spec.claim_ref is None
        ]
        available.sort(key=lambda pv: _storage_milli(pv.spec.capacity))
        for claim in claims:
            if claim.status.phase == "Bound" or claim.spec.volume_name:
                continue
            # Self-heal: a volume already reserved for this claim by an
            # earlier partial bind completes first, instead of grabbing
            # (and stranding) a second volume.
            # Match by uid, not just ns/name: a Released volume whose
            # old claim shared this claim's NAME must never self-heal
            # onto the new claim (old tenant's data).
            reserved = next(
                (
                    pv
                    for pv in volumes
                    if pv.spec.claim_ref is not None
                    and pv.status.phase != "Released"
                    and (pv.spec.claim_ref.namespace, pv.spec.claim_ref.name)
                    == (claim.metadata.namespace, claim.metadata.name)
                    and (
                        not pv.spec.claim_ref.uid
                        or pv.spec.claim_ref.uid == claim.metadata.uid
                    )
                ),
                None,
            )
            if reserved is not None:
                if self._bind(reserved, claim):
                    bound += 1
                    _SYNCS.inc(result="bound")
                continue
            want = _storage_milli(
                claim.spec.resources.requests or claim.spec.resources.limits
            )
            modes = set(claim.spec.access_modes)
            match = None
            for pv in available:
                if _storage_milli(pv.spec.capacity) < want:
                    continue
                if not modes.issubset(set(pv.spec.access_modes)):
                    continue
                match = pv
                break
            if match is None:
                continue
            if self._bind(match, claim):
                available.remove(match)
                bound += 1
                _SYNCS.inc(result="bound")
        return bound

    def _bind(self, pv, claim) -> bool:
        ref = pv.spec.claim_ref
        already_reserved = ref is not None and (ref.namespace, ref.name) == (
            claim.metadata.namespace,
            claim.metadata.name,
        )
        if not already_reserved:
            pv.spec.claim_ref = ObjectReference(
                kind="PersistentVolumeClaim",
                namespace=claim.metadata.namespace,
                name=claim.metadata.name,
                uid=claim.metadata.uid,
            )
            try:
                pv = self.client.update("persistentvolumes", pv)
            except APIError:
                return False
        if pv.status.phase != "Bound":
            pv.status.phase = "Bound"
            self._put_pv_status(pv)
        claim.spec.volume_name = pv.metadata.name
        try:
            claim = self.client.update(
                "persistentvolumeclaims", claim, namespace=claim.metadata.namespace
            )
        except APIError as e:
            if e.code == 404:
                # Claim vanished: roll the volume back to Available.
                # (On transient errors the reservation stands — the
                # self-heal path in sync_once completes it next pass.)
                self._rollback(pv.metadata.name)
            return False
        claim.status.phase = "Bound"
        claim.status.capacity = dict(pv.spec.capacity)
        claim.status.access_modes = list(pv.spec.access_modes)
        try:
            self.client.update_status(
                "persistentvolumeclaims", claim, namespace=claim.metadata.namespace
            )
        except APIError:
            pass
        return True

    def _rollback(self, pv_name: str) -> None:
        """Return a reserved volume to Available. GET-retry (guaranteed
        update): the status writes in _bind bumped the resourceVersion
        past any copy we hold, so updating a stale object would always
        CAS-conflict and strand the volume claimRef'd but Available."""
        for _ in range(3):
            try:
                fresh = self.client.get("persistentvolumes", pv_name)
            except APIError:
                return
            fresh.spec.claim_ref = None
            try:
                fresh = self.client.update("persistentvolumes", fresh)
            except APIError as e:
                if e.code == 409:
                    continue
                return
            fresh.status.phase = "Available"
            self._put_pv_status(fresh)
            return

    def _release(self, pv) -> None:
        # Every reclaim policy goes through Released: Recycle volumes
        # are picked up from there by the PersistentVolumeRecycler
        # (scrub THEN re-pool — returning one to Available before the
        # scrub would hand the old tenant's data to the next claim);
        # Retain (and Delete, modeled as Retain + operator action)
        # stays Released forever.
        pv.status.phase = "Released"
        self._put_pv_status(pv)
        _SYNCS.inc(result="released")

    def _put_pv_status(self, pv) -> None:
        try:
            self.client.update_status("persistentvolumes", pv)
        except APIError:
            pass
