"""PersistentVolumeRecycler: scrub Released Recycle-policy volumes
back into the Available pool.

Reference: pkg/volumeclaimbinder/persistent_volume_recycler.go — a
Released volume whose reclaim policy is Recycle is handed to its
volume plugin's recycler (the reference launches a scrub pod that
rm -rf's the volume contents, pv_recycler.go in pkg/volume/host_path),
then returned to the pool: claimRef cleared, phase back to Available,
so the NEXT claim can bind it without inheriting the old tenant's
data. Retain volumes stay Released forever (operator action).

Plugin recyclability is a probe, like the reference's
findRecyclablePluginBySpec (persistent_volume_claim_binder_test.go:
202-204): host_path is recyclable — the scrub is real deletion of the
directory's CONTENTS on this process substrate (the directory itself
survives: it is the volume). Sources with no recycler (NFS, cloud
disks) send the volume to Failed with a message, matching the
reference's error path, instead of silently re-pooling dirty storage.
"""

from __future__ import annotations

import logging
import os
import shutil
import threading
from typing import Callable, Optional

from kubernetes_tpu.server.api import APIError
from kubernetes_tpu.utils import metrics

_LOG = logging.getLogger("kubernetes_tpu.controllers.pvrecycler")

_RECYCLES = metrics.DEFAULT.counter(
    "pv_recycler_total", "PV recycler outcomes", ("result",)
)


def scrub_directory(path: str) -> None:
    """Delete the CONTENTS of `path`, keeping the directory.

    Refuses the filesystem root and missing/non-directory paths loudly:
    a malformed PV spec must fail the recycle (-> Failed phase), never
    wander the host deleting things.
    """
    real = os.path.realpath(path)
    if real == os.path.sep:
        raise OSError(f"refusing to scrub filesystem root ({path!r})")
    if not os.path.isdir(real):
        raise OSError(f"scrub target {path!r} is not a directory")
    for entry in os.listdir(real):
        full = os.path.join(real, entry)
        if os.path.isdir(full) and not os.path.islink(full):
            shutil.rmtree(full)
        else:
            os.unlink(full)


class PersistentVolumeRecycler:
    """Control loop pairing with PersistentVolumeClaimBinder (which
    moves Bound -> Released on claim deletion; this loop moves
    Released+Recycle -> scrub -> Available)."""

    def __init__(self, client, sync_period: float = 2.0):
        self.client = client
        self.sync_period = sync_period
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "PersistentVolumeRecycler":
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=3)

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.sync_once()
            except Exception:
                _LOG.exception("volume recycle pass failed")
                _RECYCLES.inc(result="error")
            self._stop.wait(self.sync_period)

    def sync_once(self) -> int:
        """Recycle every Released Recycle-policy volume; returns how
        many returned to Available."""
        volumes, _ = self.client.list("persistentvolumes")
        recycled = 0
        for pv in volumes:
            if pv.status.phase != "Released":
                continue
            if pv.spec.persistent_volume_reclaim_policy != "Recycle":
                continue
            scrub = self._scrubber_for(pv)
            if scrub is None:
                self._fail(
                    pv,
                    "no recyclable volume plugin for this source "
                    "(reference: findRecyclablePluginBySpec error path)",
                )
                continue
            try:
                scrub()
            except OSError as e:
                self._fail(pv, f"scrub failed: {e}")
                continue
            if self._repool(pv.metadata.name):
                recycled += 1
                _RECYCLES.inc(result="recycled")
        return recycled

    def _scrubber_for(self, pv) -> Optional[Callable[[], None]]:
        src = pv.spec.persistent_volume_source
        hp = getattr(src, "host_path", None)
        if hp is not None and hp.path:
            return lambda: scrub_directory(hp.path)
        return None

    def _repool(self, pv_name: str) -> bool:
        """Clear claimRef and set Available. GET-retry under CAS: the
        binder's status writes race ours."""
        for _ in range(3):
            try:
                fresh = self.client.get("persistentvolumes", pv_name)
            except APIError:
                return False
            fresh.spec.claim_ref = None
            try:
                fresh = self.client.update("persistentvolumes", fresh)
            except APIError as e:
                if e.code == 409:
                    continue
                return False
            fresh.status.phase = "Available"
            fresh.status.message = ""
            try:
                self.client.update_status("persistentvolumes", fresh)
            except APIError:
                pass
            return True
        return False

    def _fail(self, pv, message: str) -> None:
        pv.status.phase = "Failed"
        pv.status.message = message
        try:
            self.client.update_status("persistentvolumes", pv)
        except APIError:
            pass
        _RECYCLES.inc(result="failed")
