"""ServiceController: drives the cloud provider's load-balancer surface
for Services of type LoadBalancer.

Reference: pkg/cloudprovider/servicecontroller/servicecontroller.go —
watch services; for type=LoadBalancer ensure a provider LB pointing at
the cluster's (ready) hosts and publish the allocated ingress in
service.status; keep the host list in sync as nodes come and go; tear
the LB down when the service is deleted or changes type.

TPU analog: the provider's "load balancer" is a fabric ingress — portal
rules programmed at the slice edge (cloudprovider/tpu.py) — but the
control loop is provider-agnostic through LoadBalancerStub.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional

from kubernetes_tpu.client.cache import Informer
from kubernetes_tpu.models import serde
from kubernetes_tpu.models.objects import Node, Service
from kubernetes_tpu.server.api import APIError
from kubernetes_tpu.utils import metrics

_LOG = logging.getLogger("kubernetes_tpu.controllers.servicelb")

_SYNCS = metrics.DEFAULT.counter(
    "service_lb_syncs_total", "service LB sync outcomes", ("action",)
)


def _decode_service(wire: dict) -> Service:
    return serde.from_wire(Service, wire)


def _decode_node(wire: dict) -> Node:
    return serde.from_wire(Node, wire)


def _node_ready(node: Node) -> bool:
    for c in node.status.conditions:
        if c.type == "Ready":
            return c.status == "True"
    return False


class ServiceController:
    def __init__(self, client, provider, sync_period: float = 1.0):
        self.client = client
        self.lb = provider.load_balancer()
        self.sync_period = sync_period
        self._dirty = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        mark = lambda o: self._dirty.set()  # noqa: E731
        self.services = Informer(
            client, "services", decode=_decode_service,
            on_add=mark, on_update=mark, on_delete=mark,
        )
        self.nodes = Informer(
            client, "nodes", decode=_decode_node,
            on_add=mark, on_update=mark, on_delete=mark,
        )

    # -- lifecycle ----------------------------------------------------

    def start(self) -> "ServiceController":
        if self.lb is None:
            raise ValueError("cloud provider has no load balancer surface")
        self.services.start()
        self.nodes.start()
        self.services.wait_for_sync()
        self.nodes.wait_for_sync()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._dirty.set()
        self.services.stop()
        self.nodes.stop()
        if self._thread:
            self._thread.join(timeout=2)

    # -- reconcile ----------------------------------------------------

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._dirty.wait(self.sync_period)
            self._dirty.clear()
            if self._stop.is_set():
                return
            try:
                self.sync()
                _SYNCS.inc(action="ok")
            except Exception:
                # Crash containment, but visibly: a permanently failing
                # reconcile must show up in /metrics.
                _LOG.exception("service LB sync pass failed")
                _SYNCS.inc(action="error")

    def _hosts(self) -> List[str]:
        return sorted(
            n.metadata.name
            for n in self.nodes.store.list()
            if _node_ready(n)
        )

    @staticmethod
    def _lb_name(svc: Service) -> str:
        """Unique, DNS-safe provider LB name. The namespace/name pair
        is disambiguated with a short hash: a plain '-' join collides
        ('team-a'/'api' vs 'team'/'a-api'), and the reference derives
        LB names from the service UID for the same reason."""
        import hashlib

        key = f"{svc.metadata.namespace or 'default'}/{svc.metadata.name}"
        suffix = hashlib.sha1(key.encode()).hexdigest()[:6]
        return f"{key.replace('/', '-')}-{suffix}"

    def _publish_status(self, svc: Service, ingress) -> None:
        """Write status.loadBalancer.ingress if it differs (copy first:
        the informer cache's object is shared — mutating it in place
        would make a FAILED status write look already-applied)."""
        import copy

        wanted = {"ingress": ingress} if ingress else {}
        current = (svc.status or {}).get("loadBalancer") or {}
        if current == wanted:
            return
        patched = copy.deepcopy(svc)
        patched.status = dict(patched.status or {})
        patched.status["loadBalancer"] = wanted
        try:
            self.client.update_status(
                "services", patched,
                namespace=svc.metadata.namespace or "default",
            )
        except APIError:
            pass  # retried next tick (cache stays unmodified)

    def sync(self) -> None:
        hosts = self._hosts()
        wanted_names = set()
        for svc in self.services.store.list():
            if svc.spec.type != "LoadBalancer":
                # Type changed away from LoadBalancer: the provider LB
                # is collected below, and the published ingress must go
                # with it (a live-looking ingress pointing at a deleted
                # LB is worse than none).
                self._publish_status(svc, None)
                continue
            name = self._lb_name(svc)
            wanted_names.add(name)
            if name not in self.lb.balancers:
                ingress = self.lb.ensure(name, hosts)
            else:
                # Already provisioned: only reprogram on host drift
                # (a real provider call per service per tick is waste).
                if self.lb.balancers.get(name) != hosts:
                    self.lb.update_hosts(name, hosts)
                ingress = self.lb.address(name)
            self._publish_status(svc, [{"ip": ingress}])
        # Reconcile teardown against the PROVIDER's state, not an
        # in-memory map: a controller restart must still collect LBs
        # whose service vanished while it was down. This controller
        # owns the provider's whole LB surface (reference
        # servicecontroller owns cloud LBs matching its naming).
        for name in list(self.lb.balancers):
            if name not in wanted_names:
                self.lb.delete(name)
