"""NamespaceManager: two-phase namespace deletion.

Reference: pkg/namespace/namespace_controller.go — when a namespace
enters Terminating (deletionTimestamp set by the registry while
spec.finalizers is non-empty), purge all namespaced content, clear the
'kubernetes' finalizer via the finalize subresource, then delete the
now-finalizer-free namespace for real.
"""

from __future__ import annotations

import logging
import threading
from typing import List, Optional

from kubernetes_tpu.server.api import APIError
from kubernetes_tpu.utils import metrics

# Content purged on namespace termination (reference
# namespace_controller.go deleteAllContent; extended to every
# namespaced resource this framework serves).
_NAMESPACED_RESOURCES = [
    "pods",
    "replicationcontrollers",
    "services",
    "endpoints",
    "secrets",
    "serviceaccounts",
    "limitranges",
    "resourcequotas",
    "persistentvolumeclaims",
    "podtemplates",
    "events",
]

_LOG = logging.getLogger("kubernetes_tpu.controllers.namespace")

_SYNCS = metrics.DEFAULT.counter(
    "namespace_controller_syncs_total", "namespace sync passes", ("result",)
)


class NamespaceManager:
    def __init__(self, client, sync_period: float = 1.0):
        self.client = client
        self.sync_period = sync_period
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "NamespaceManager":
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=3)

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.sync_once()
            except Exception:
                _LOG.exception("namespace lifecycle sync pass failed")
                _SYNCS.inc(result="error")
            self._stop.wait(self.sync_period)

    def sync_once(self) -> int:
        """One pass over all namespaces; returns count finalized (a
        namespace still held by a foreign finalizer doesn't count)."""
        done = 0
        namespaces, _ = self.client.list("namespaces")
        for ns in namespaces:
            if ns.status.phase != "Terminating":
                continue
            if self._terminate(ns.metadata.name, ns.spec.finalizers):
                done += 1
                _SYNCS.inc(result="terminated")
            else:
                _SYNCS.inc(result="blocked")
        return done

    def _terminate(self, name: str, finalizers: List[str]) -> bool:
        for resource in _NAMESPACED_RESOURCES:
            try:
                items, _ = self.client.list(resource, namespace=name)
            except APIError:
                continue
            for obj in items:
                try:
                    self.client.delete(
                        resource, obj.metadata.name, namespace=name
                    )
                except APIError:
                    pass  # already gone / racing deleter
        # Remove only OUR finalizer; foreign finalizers (guarding
        # external cleanup owned by other controllers) must stay until
        # their owners remove them (namespace_controller.go finalize).
        remaining = [f for f in finalizers if f != "kubernetes"]
        if remaining != list(finalizers):
            try:
                self.client.finalize_namespace(name, remaining)
            except APIError:
                return False
        if remaining:
            return False  # someone else's finalizer still pending
        try:
            self.client.delete("namespaces", name)
        except APIError:
            return False
        return True
