"""Autoscaler: elastic node pools closing the capacity loop.

The capacity plane (PR 16) distinguishes two starvation modes: a
fragmented cluster (free capacity exists as unusable shards — the
descheduler's job) and a genuinely full one (``capacity_zero_headroom
_ticks_total`` burning while pods wait — no reshuffle can fix it,
only capacity can). This controller handles the second mode, and the
reverse: sustained low utilization with an empty backlog means paid
capacity idling, so the pool shrinks back.

Grow: ``grow_after`` consecutive polls observing starvation (the
zero-headroom counter advanced since the last poll, OR a non-empty
pending backlog with no schedulable headroom signal) add
``grow_step`` nodes through the pool provider.

Shrink: ``shrink_after`` consecutive polls of mean live-node CPU
utilization below ``low_util`` with an EMPTY backlog start a drain:
the emptiest pool node is cordoned (``spec.unschedulable`` — the
columns drop it from every solve), its pods move out through the
descheduler's graceful journal/evict/nominate path (``drain_node`` —
the SAME eviction machinery as defrag, never a force-delete), and
only once the node is observably empty does the provider retire it.
A node that refuses to empty stays cordoned and the drain retries
next poll — shrink never races its own evictions.

The pool provider is duck-typed (see tools/soak.py's hollow-node
pool): ``name``, ``size()``, ``grow(n) -> [node_names]``,
``shrink(node_name)``. Providers own node object lifecycle (a real
provider deregisters the kubelet; the hollow pool stops the thread
and deletes the Node).
"""

from __future__ import annotations

import logging
import threading
from typing import List, Optional

import numpy as np

from kubernetes_tpu.server.api import APIError
from kubernetes_tpu.utils import metrics
from kubernetes_tpu.utils.capacity import ZERO_HEADROOM, cluster_columns

_LOG = logging.getLogger("kubernetes_tpu.controllers.autoscaler")

POOL_SIZE = metrics.DEFAULT.gauge(
    "autoscaler_pool_size",
    "Current node count of each elastic pool",
    ("pool",),
)
SCALE_EVENTS = metrics.DEFAULT.counter(
    "autoscaler_scale_events_total",
    "Pool resize decisions by direction (up/down)",
    ("direction",),
)
_SYNCS = metrics.DEFAULT.counter(
    "autoscaler_syncs_total", "Autoscaler evaluation passes", ("result",)
)


class Autoscaler:
    """Periodic pool-size controller. ``sync_once()`` works without
    ``start()`` — tests and the soak harness drive polls directly."""

    def __init__(
        self,
        client,
        pool,
        sync_period: float = 10.0,
        min_size: int = 1,
        max_size: int = 16,
        grow_after: int = 3,
        grow_step: int = 1,
        shrink_after: int = 6,
        low_util: float = 0.25,
        descheduler=None,
    ):
        self.client = client
        self.pool = pool
        self.sync_period = sync_period
        self.min_size = int(min_size)
        self.max_size = int(max_size)
        self.grow_after = int(grow_after)
        self.grow_step = int(grow_step)
        self.shrink_after = int(shrink_after)
        self.low_util = float(low_util)
        if descheduler is None:
            from kubernetes_tpu.controllers.descheduler import Descheduler

            descheduler = Descheduler(client)
        self.descheduler = descheduler
        self._starve_polls = 0
        self._idle_polls = 0
        self._last_burn: Optional[float] = None
        self._draining: Optional[str] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        POOL_SIZE.set(self.pool.size(), pool=self.pool.name)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "Autoscaler":
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=3)

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.sync_once()
                _SYNCS.inc(result="ok")
            except Exception:
                _LOG.exception("autoscaler sync failed")
                _SYNCS.inc(result="error")
            self._stop.wait(self.sync_period)

    # -- the poll ----------------------------------------------------------

    def sync_once(self) -> dict:
        """One evaluation: read the cluster, fold the starvation/idle
        streak counters, act when a streak completes. Returns the poll
        summary (the soak harness asserts on it)."""
        nodes, _ = self.client.list("nodes")
        pods, _ = self.client.list("pods")
        cols, names = cluster_columns(nodes, pods)
        pending = sum(
            1
            for p in pods
            if not p.spec.node_name
            and p.status.phase not in ("Succeeded", "Failed")
        )

        burn = ZERO_HEADROOM.value()
        burned = self._last_burn is not None and burn > self._last_burn
        self._last_burn = burn

        # cpu_fit is the greedy-fit CHARGE (capacity_report semantics):
        # utilization = charged/capacity, same as util_cpu in-kernel.
        live = np.asarray(cols["sched"], bool)
        caps = np.asarray(cols["cpu_cap"], np.float32)
        fits = np.asarray(cols["cpu_fit"], np.float32)
        util = 0.0
        mask = live & (caps > 0)
        if mask.any():
            util = float(np.mean(np.clip(fits[mask] / caps[mask], 0.0, 1.0)))

        starving = burned or pending > 0
        idle = not pending and util < self.low_util
        if starving:
            self._starve_polls += 1
            self._idle_polls = 0
        elif idle:
            self._idle_polls += 1
            self._starve_polls = 0
        else:
            self._starve_polls = 0
            self._idle_polls = 0

        summary = {
            "kind": "AutoscalerPoll",
            "pool": self.pool.name,
            "size": self.pool.size(),
            "pending": pending,
            "mean_cpu_util": round(util, 4),
            "starve_polls": self._starve_polls,
            "idle_polls": self._idle_polls,
            "action": "none",
        }

        if self._draining is not None:
            summary["action"] = self._continue_drain(pods)
        elif (
            self._starve_polls >= self.grow_after
            and self.pool.size() < self.max_size
        ):
            step = min(self.grow_step, self.max_size - self.pool.size())
            added = self.pool.grow(step)
            self._starve_polls = 0
            SCALE_EVENTS.inc(direction="up")
            summary["action"] = "grow"
            summary["added"] = list(added or [])
        elif (
            self._idle_polls >= self.shrink_after
            and self.pool.size() > self.min_size
        ):
            summary["action"] = self._start_drain(nodes, pods)

        POOL_SIZE.set(self.pool.size(), pool=self.pool.name)
        summary["size"] = self.pool.size()
        return summary

    # -- shrink machinery --------------------------------------------------

    def _pool_nodes(self, nodes) -> List:
        members = set(getattr(self.pool, "node_names", lambda: [])() or [])
        if members:
            return [n for n in nodes if n.metadata.name in members]
        return list(nodes)

    def _start_drain(self, nodes, pods) -> str:
        """Cordon the emptiest pool node and kick its drain."""
        counts = {}
        for p in pods:
            if p.spec.node_name and p.status.phase not in (
                "Succeeded",
                "Failed",
            ):
                counts[p.spec.node_name] = counts.get(p.spec.node_name, 0) + 1
        candidates = [
            n
            for n in self._pool_nodes(nodes)
            if not (n.spec.unschedulable if n.spec else False)
        ]
        if not candidates:
            return "none"
        victim = min(
            candidates,
            key=lambda n: (counts.get(n.metadata.name, 0), n.metadata.name),
        )
        name = victim.metadata.name
        try:
            self.client.patch(
                "nodes", name, {"spec": {"unschedulable": True}}
            )
        except APIError:
            return "none"
        self._draining = name
        self.descheduler.drain_node(name)
        return "drain"

    def _continue_drain(self, pods) -> str:
        """Finish (or keep pushing) the in-flight drain: retire the
        node only once nothing non-terminal remains bound to it."""
        name = self._draining
        remaining = [
            p
            for p in pods
            if p.spec.node_name == name
            and p.status.phase not in ("Succeeded", "Failed")
        ]
        if remaining:
            self.descheduler.drain_node(name)
            return "draining"
        self.pool.shrink(name)
        self._draining = None
        self._idle_polls = 0
        SCALE_EVENTS.inc(direction="down")
        return "shrink"
