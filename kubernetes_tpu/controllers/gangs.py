"""GangController: PodGroup lifecycle (status, aging, events).

No direct reference analog (the closest shape is the sig-scheduling
coscheduling controller's PodGroup status loop); structurally it is a
standard level-triggered controller like controllers/resourcequota.py:
every sync period it reconciles each PodGroup's observed membership
against its declared gang intent.

Per group, each pass:

- recounts members (pods carrying POD_GROUP_LABEL in the group's
  namespace) and bound members (spec.nodeName set), publishing both in
  status;
- flips phase to Scheduled (+ event) once bound >= minMember — the
  gang landed, whoever solved it;
- ages groups stuck Pending past spec.scheduleTimeoutSeconds: marks
  them Unschedulable, emits a GangTimeout event, and bumps
  gang_solve_outcomes_total{outcome="timeout"}. Unschedulable is NOT
  terminal — member pods stay in the scheduler's backoff requeue loop,
  so a later successful gang solve flips the group straight to
  Scheduled (the "requeue" half of age-out: nothing needs resubmitting).
"""

from __future__ import annotations

import logging
import threading
import time
from datetime import datetime, timezone
from typing import Optional

from kubernetes_tpu.models.objects import POD_GROUP_LABEL
from kubernetes_tpu.server.api import APIError
from kubernetes_tpu.utils import metrics

_LOG = logging.getLogger("kubernetes_tpu.controllers.gangs")

_SYNCS = metrics.DEFAULT.counter(
    "gang_controller_syncs_total", "PodGroup sync passes", ("result",)
)
#: Groups currently Pending/Unschedulable, refreshed every sync — the
#: backlog-depth signal dashboards watch for gang starvation.
_PENDING = metrics.DEFAULT.gauge(
    "gang_pending_groups", "PodGroups currently Pending"
)

PENDING = "Pending"
SCHEDULED = "Scheduled"
UNSCHEDULABLE = "Unschedulable"


def _parse_ts(ts: str) -> Optional[float]:
    if not ts:
        return None
    try:
        return (
            datetime.strptime(ts, "%Y-%m-%dT%H:%M:%SZ")
            .replace(tzinfo=timezone.utc)
            .timestamp()
        )
    except ValueError:
        return None


class GangController:
    def __init__(self, client, sync_period: float = 1.0, pods_informer=None):
        self.client = client
        self.sync_period = sync_period
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Informer-fed caches: the RUNNING controller reads groups and
        # member pods from watch-fed stores instead of two cluster-wide
        # LISTs per sync period (at a 1s period over 50k pods the
        # repeated full fetch was the controller's whole API budget).
        # `pods_informer` SHARES another controller's typed pods
        # informer (the manager passes ReplicationManager's) — a
        # controller-manager process must not run three independent
        # all-pods watches each decoding every event. A direct
        # sync_once() without start() (tests, one-shot reconciles)
        # falls back to read-through LISTs.
        self.podgroups = None
        self.pods = pods_informer
        self._owns_pods = pods_informer is None

    def start(self) -> "GangController":
        from kubernetes_tpu.client.cache import Informer
        from kubernetes_tpu.models import serde
        from kubernetes_tpu.models.objects import Pod, PodGroup

        self.podgroups = Informer(
            self.client, "podgroups",
            decode=lambda w: serde.from_wire(PodGroup, w),
        ).start()
        if self.pods is None:
            self.pods = Informer(
                self.client, "pods",
                decode=lambda w: serde.from_wire(Pod, w),
            ).start()
            self.pods.wait_for_sync()
        self.podgroups.wait_for_sync()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self.podgroups is not None:
            self.podgroups.stop()
        if self.pods is not None and self._owns_pods:
            self.pods.stop()  # a shared informer is its owner's to stop
        if self._thread:
            self._thread.join(timeout=3)

    def _list_groups(self) -> list:
        if self.podgroups is not None:
            return self.podgroups.store.list()
        groups, _ = self.client.list("podgroups")
        return groups

    def _list_pods(self) -> list:
        if self.pods is not None:
            return self.pods.store.list()
        return self.client.list("pods")[0]

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.sync_once()
                _SYNCS.inc(result="ok")
            except Exception:
                _LOG.exception("gang controller sync pass failed")
                _SYNCS.inc(result="error")
            self._stop.wait(self.sync_period)

    def sync_once(self, now: Optional[float] = None) -> int:
        """One reconcile pass over every PodGroup; returns groups whose
        status changed. `now` is injectable for aging tests."""
        from kubernetes_tpu.scheduler.gang import OUTCOMES, pod_is_live

        now = time.time() if now is None else now
        changed = 0
        pending = 0
        groups = self._list_groups()
        if not groups:
            _PENDING.set(0)
            return 0
        # ONE pass over the pod cache per sync, bucketed host-side: a
        # per-group label-selected LIST is a full server-side scan of
        # the namespace's pods EACH (api.list predicate-filters the
        # whole collection), which at the 50k-pod target and G groups
        # costs G full scans per second at steady state. With the
        # informer started this doesn't even leave the process.
        by_group: dict = {}
        for p in self._list_pods():
            g = (p.metadata.labels or {}).get(POD_GROUP_LABEL, "")
            if g:
                by_group.setdefault(
                    (p.metadata.namespace or "default", g), []
                ).append(p)
        for pg in groups:
            ns = pg.metadata.namespace or "default"
            name = pg.metadata.name
            labeled = by_group.get((ns, name), [])
            # Live members only (same rule as admission and the solve's
            # bound credit): a crashed member keeps label + nodeName but
            # satisfies nothing — counting it would pin a dead gang
            # "Scheduled" forever and mute GangTimeout.
            members = [p for p in labeled if pod_is_live(p)]
            bound = sum(1 for p in members if p.spec.node_name)
            phase = pg.status.phase or PENDING
            message = pg.status.message
            # The current Pending stint's start: aging runs against
            # THIS, not creationTimestamp — a gang that re-pends after
            # running gets a full fresh timeout window.
            pending_since = (
                pg.status.pending_since or pg.metadata.creation_timestamp
            )
            if bound >= pg.spec.min_member:
                if phase != SCHEDULED:
                    phase = SCHEDULED
                    message = (
                        f"{bound}/{pg.spec.min_member} minMember pods bound"
                    )
                    self._event(
                        pg, "GangScheduled",
                        f'pod group "{ns}/{name}" fully bound '
                        f"({bound} members)",
                    )
            elif phase == SCHEDULED:
                # A bound gang lost members (deletes/evictions) below
                # minMember: it is pending again and ages from now.
                phase = PENDING
                message = f"bound fell to {bound}/{pg.spec.min_member}"
                pending_since = time.strftime(
                    "%Y-%m-%dT%H:%M:%SZ", time.gmtime(now)
                )
            elif phase == PENDING and pg.spec.schedule_timeout_seconds > 0:
                since = _parse_ts(pending_since)
                if (
                    since is not None
                    and now - since > pg.spec.schedule_timeout_seconds
                ):
                    phase = UNSCHEDULABLE
                    message = (
                        f"still {bound}/{pg.spec.min_member} bound after "
                        f"{pg.spec.schedule_timeout_seconds}s; member pods "
                        "remain queued and will gang-bind if capacity frees"
                    )
                    OUTCOMES.inc(outcome="timeout")
                    self._event(
                        pg, "GangTimeout",
                        f'pod group "{ns}/{name}" unschedulable: {message}',
                    )
            if phase in (PENDING, UNSCHEDULABLE):
                pending += 1
            if (
                phase == pg.status.phase
                and bound == pg.status.bound
                and len(members) == pg.status.members
                and pending_since == (
                    pg.status.pending_since
                    or pg.metadata.creation_timestamp
                )
            ):
                continue  # unchanged: skip the write, don't wake watchers
            try:
                self.client.update_status(
                    "podgroups",
                    {
                        "kind": "PodGroup",
                        "metadata": {"name": name, "namespace": ns},
                        "status": {
                            "phase": phase,
                            "members": len(members),
                            "bound": bound,
                            "message": message,
                            "pendingSince": pending_since,
                        },
                    },
                    namespace=ns,
                )
                changed += 1
            except APIError:
                pass  # deleted mid-sync / racing writer: next pass fixes
        _PENDING.set(pending)
        return changed

    def _event(self, pg, reason: str, message: str) -> None:
        try:
            self.client.record_event(
                pg, reason, message,
                source="gang-controller",
                namespace=pg.metadata.namespace or "default",
            )
        except Exception:  # ktlint: disable=KT003
            pass  # events are observability, never control flow
