"""Descheduler: the continuous-rebalancing control loop.

Closes the loop the capacity plane (PR 16) only observed: when the
cluster fragmentation score crosses the threshold while pods are
waiting, the free capacity exists but is unusable shards — no
scheduling decision can fix it, only moving bound pods can. Each
cycle re-solves the bound cluster with the ``plan_moves`` defrag
kernel (utils/rebalance.build_plan) and executes the minimal-move
plan through the SAME graceful-eviction + nomination machinery the
preemption pass uses (scheduler/daemon._preempt) — the descheduler
never force-deletes anything.

Move protocol (crash-safe by construction):

1. journal the move intent as a PodTemplate labeled
   ``REBALANCE_JOURNAL_LABEL`` (value = destination node) carrying the
   pod's full metadata+spec — written BEFORE the eviction, so from
   this point the move can always be replayed;
2. graceful eviction (the pods/{name}/eviction subresource; 404 =
   already gone, counts as evicted);
3. ``DESCHED_MOVE_CRASH`` fault site fires HERE — between the
   eviction and the recreation, the exact window where a crash would
   otherwise strand the pod;
4. wait for the pod to leave the store (kubelet confirms the grace
   deadline for grace > 0); a timeout leaves the journal in place for
   recovery instead of guessing;
5. recreate the pod (same name, NEW uid — bind-immutability-per-uid
   is preserved, the binding belongs to the old incarnation) stamped
   with ``REBALANCE_DEST_ANNOTATION`` (the columnar staging honors it
   as a HostName pin) and a nominatedNodeName patch, so the micro-tick
   daemon rebinds it at the planned destination;
6. delete the journal entry — the move is durable.

Recovery runs at the START of every cycle: orphaned journal entries
(step 3/4 crash) whose pod is missing are replayed — the pod is
recreated and re-pends; entries whose pod exists are stale and
dropped. A crashed defrag therefore strands nothing, which is exactly
what the ``rebalance_stranded_pods`` SLO gate asserts.

Gang moves: build_plan already made move groups gang-atomic; this
controller additionally commits a gang group's bindings itself via
``bind_bulk(atomic=True)`` after recreating all members — a slice
lands at its destinations as one transaction instead of trickling
through per-pod scheduler ticks. Singleton moves ride the nomination.

Disruption is bounded PDB-style: at most ``disruption_cap`` evictions
per tick (a whole gang group counts against the cap; the first group
of a tick always runs so a gang larger than the cap can still ever
move). Stale nominations are swept: a recreated pod still Pending
past ``nomination_ttl_s`` gets its pin cleared (annotation blanked)
so a destination that filled up concurrently cannot wedge it — it
re-enters the normal solve as a free pod.
"""

from __future__ import annotations

import copy
import logging
import threading
import time
from datetime import datetime, timezone
from typing import Dict, List, Optional, Sequence

from kubernetes_tpu.models.objects import (
    REBALANCE_DEST_ANNOTATION,
    REBALANCE_JOURNAL_LABEL,
    ObjectMeta,
    Pod,
    PodStatus,
    PodTemplate,
    PodTemplateSpec,
    pod_full_key,
)
from kubernetes_tpu.server.api import APIError
from kubernetes_tpu.utils import capacity as capacity_mon
from kubernetes_tpu.utils import faults, flightrecorder, metrics
from kubernetes_tpu.utils import rebalance as rebalance_mon
from kubernetes_tpu.utils.capacity import cluster_columns
from kubernetes_tpu.utils.rebalance import (
    DEFAULT_MOVE_BUDGET,
    build_plan,
    fragment_score,
)

_LOG = logging.getLogger("kubernetes_tpu.controllers.descheduler")

_SYNCS = metrics.DEFAULT.counter(
    "descheduler_syncs_total", "Descheduler sync passes", ("result",)
)

#: Journal PodTemplate name prefix (one entry per in-flight move).
JOURNAL_PREFIX = "rebalance-move-"


def _parse_ts(ts: str) -> Optional[float]:
    if not ts:
        return None
    try:
        return (
            datetime.strptime(ts, "%Y-%m-%dT%H:%M:%SZ")
            .replace(tzinfo=timezone.utc)
            .timestamp()
        )
    except ValueError:
        return None


class Descheduler:
    """Periodic/triggered defragmenter. ``sync_once()`` works without
    ``start()`` (read-through LISTs) — tests and ``drain_node`` drive
    it directly; the started thread adds the periodic trigger."""

    def __init__(
        self,
        client,
        sync_period: float = 10.0,
        frag_threshold: float = 0.5,
        move_budget: int = DEFAULT_MOVE_BUDGET,
        disruption_cap: int = 4,
        grace_period_seconds: int = 0,
        nomination_ttl_s: float = 30.0,
        wait_timeout_s: float = 5.0,
    ):
        self.client = client
        self.sync_period = sync_period
        self.frag_threshold = float(frag_threshold)
        self.move_budget = int(move_budget)
        self.disruption_cap = int(disruption_cap)
        self.grace_period_seconds = int(grace_period_seconds)
        self.nomination_ttl_s = float(nomination_ttl_s)
        self.wait_timeout_s = float(wait_timeout_s)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "Descheduler":
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=3)

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.sync_once()
                _SYNCS.inc(result="ok")
            except Exception:
                _LOG.exception("descheduler sync failed")
                _SYNCS.inc(result="error")
            self._stop.wait(self.sync_period)

    # -- the cycle ---------------------------------------------------------

    def sync_once(
        self, force: bool = False, forced_nodes: Sequence[str] = ()
    ) -> dict:
        """One full pass: journal recovery, nomination sweep, trigger
        check, plan, execute, measure. Returns the cycle summary."""
        recovered = self.recover()
        self._sweep_nominations()

        nodes, _ = self.client.list("nodes")
        pods, _ = self.client.list("pods")
        cols, names = cluster_columns(nodes, pods)
        probes = capacity_mon.DEFAULT.probe_set()
        pending = [
            p
            for p in pods
            if not p.spec.node_name
            and p.status.phase not in ("Succeeded", "Failed")
        ]

        plan = build_plan(
            cols,
            names,
            pods,
            probes,
            move_budget=self.move_budget,
            forced_nodes=forced_nodes,
        )
        summary = {
            "kind": "DeschedulerCycle",
            "recovered": recovered,
            "triggered": False,
            "moves_executed": 0,
        }
        if plan is None:
            return summary
        rebalance_mon.DEFAULT.record_plan(plan)
        if forced_nodes:
            # Drain semantics: evacuate the named nodes, nothing more.
            # The kernel also surfaces opportunistic gain-positive
            # moves elsewhere in the cluster; executing those during a
            # drain would evict pods the caller never asked to touch
            # (and a draining autoscaler would then see its own
            # re-pending evictees as backlog and grow right back).
            keep = {m["group"] for m in plan["moves"] if m["forced"]}
            plan = dict(plan)
            plan["moves"] = [m for m in plan["moves"] if m["group"] in keep]

        triggered = (
            force
            or bool(forced_nodes)
            or (plan["score_before"] >= self.frag_threshold and pending)
        )
        summary["score_before"] = plan["score_before"]
        if not triggered or not plan["moves"]:
            return summary
        summary["triggered"] = True

        executed = self._execute(plan)
        rebalance_mon.DEFAULT.record_move("planned", len(plan["moves"]))

        # Measure, don't trust: the after-score comes from a fresh
        # LIST of the post-eviction cluster, not the kernel's forecast.
        nodes, _ = self.client.list("nodes")
        pods, _ = self.client.list("pods")
        cols, _ = cluster_columns(nodes, pods)
        after = fragment_score(cols, probes)
        if after is None:
            after = plan["score_after"]
        trigger = (
            "drain" if forced_nodes else ("forced" if force else "periodic")
        )
        cycle = rebalance_mon.DEFAULT.record_cycle(
            plan["score_before"], after, executed, trigger=trigger
        )
        summary.update(cycle)
        summary["moves_executed"] = executed
        return summary

    def run_once(self, force: bool = False) -> dict:
        """Alias trigger: one forced defrag cycle (ktctl / tests)."""
        return self.sync_once(force=force)

    def drain_node(self, node_name: str) -> dict:
        """Forced cycle that empties one node (the autoscaler's
        cordon-drain half) — every pod on it moves regardless of gain,
        through the same graceful journal/evict/recreate path."""
        return self.sync_once(force=True, forced_nodes=(node_name,))

    # -- recovery + sweeps -------------------------------------------------

    def recover(self) -> int:
        """Replay orphaned move journals: an entry whose pod is gone
        means the descheduler died between eviction and recreation —
        recreate the pod now (it re-pends and binds); an entry whose
        pod exists is finished business — drop it."""
        try:
            entries, _ = self.client.list(
                "podtemplates", label_selector=REBALANCE_JOURNAL_LABEL
            )
        except APIError:
            return 0
        recovered = 0
        for entry in entries:
            labels = entry.metadata.labels or {}
            if REBALANCE_JOURNAL_LABEL not in labels:
                continue
            ns = entry.metadata.namespace or "default"
            name = entry.template.metadata.name
            if not name:
                self._delete_journal(entry.metadata.name, ns)
                continue
            try:
                self.client.get("pods", name, namespace=ns)
                exists = True
            except APIError as e:
                if e.code != 404:
                    continue  # can't tell — leave the journal alone
                exists = False
            if exists:
                self._delete_journal(entry.metadata.name, ns)
                continue
            dest = labels.get(REBALANCE_JOURNAL_LABEL, "")
            try:
                self.client.create(
                    "pods",
                    self._replacement(entry.template, dest),
                    namespace=ns,
                )
                rebalance_mon.DEFAULT.record_move("recovered")
                recovered += 1
                self._delete_journal(entry.metadata.name, ns)
            except APIError as e:
                if e.code == 409:
                    self._delete_journal(entry.metadata.name, ns)
                elif 400 <= e.code < 500:
                    # Terminal rejection: recovery is exhausted for
                    # this entry — the evicted pod is stranded (the
                    # SLO gate burns) and the journal drops so the
                    # counter can't double-burn next cycle.
                    rebalance_mon.DEFAULT.record_move("stranded")
                    self._delete_journal(entry.metadata.name, ns)
                # 5xx / transport: keep the journal, retry next cycle.
        return recovered

    def _sweep_nominations(self) -> None:
        """Settle in-flight nominations: a recreated pod that BOUND
        completes its move (annotation blanked, outcome ``rebound``);
        one still Pending past the nomination TTL has its pin cleared
        (outcome ``failed`` — the pod re-enters the solve unpinned,
        nothing is stranded)."""
        try:
            pods, _ = self.client.list("pods")
        except APIError:
            return
        now = time.time()
        for p in pods:
            dest = (p.metadata.annotations or {}).get(
                REBALANCE_DEST_ANNOTATION, ""
            )
            if not dest:
                continue
            if p.spec.node_name:
                outcome = "rebound"
            else:
                born = _parse_ts(p.metadata.creation_timestamp)
                if born is not None and now - born < self.nomination_ttl_s:
                    continue  # still within its window
                outcome = "failed"
            try:
                # Blank, don't delete: merge-patch to "" — the
                # columnar pin and the movable filter both key on
                # truthiness, and blanking needs no null semantics.
                self.client.patch(
                    "pods",
                    p.metadata.name,
                    {
                        "metadata": {
                            "annotations": {REBALANCE_DEST_ANNOTATION: ""}
                        }
                    },
                    namespace=p.metadata.namespace or "default",
                )
                rebalance_mon.DEFAULT.record_move(outcome)
            except APIError:
                continue

    # -- execution ---------------------------------------------------------

    def _execute(self, plan: dict) -> int:
        """Run the plan's move groups under the disruption cap.
        Returns evictions executed."""
        pods, _ = self.client.list("pods")
        by_key = {pod_full_key(p): p for p in pods}
        groups: Dict[str, List[dict]] = {}
        order: List[str] = []
        for m in plan["moves"]:
            if m["group"] not in groups:
                order.append(m["group"])
            groups.setdefault(m["group"], []).append(m)

        executed = 0
        for gi, g in enumerate(order):
            moves = groups[g]
            if executed and executed + len(moves) > self.disruption_cap:
                break  # PDB-style: the cap holds (first group exempt)
            is_gang = any(m["gang"] for m in moves)
            done = []
            for m in moves:
                pod = by_key.get(m["pod"])
                if pod is None:
                    continue
                if self._move(pod, m, defer_bind=is_gang):
                    executed += 1
                    done.append(m)
            if is_gang and done:
                self._commit_gang(done)
        return executed

    def _move(self, pod, m: dict, defer_bind: bool = False) -> bool:
        """One journal/evict/recreate/nominate move. True when the
        eviction landed (the disruption actually happened)."""
        ns = pod.metadata.namespace or "default"
        name = pod.metadata.name
        key = m["pod"]
        journal = PodTemplate(
            metadata=ObjectMeta(
                name=f"{JOURNAL_PREFIX}{name}",
                namespace=ns,
                labels={REBALANCE_JOURNAL_LABEL: m["to"]},
            ),
            template=PodTemplateSpec(
                metadata=ObjectMeta(
                    name=name,
                    namespace=ns,
                    labels=dict(pod.metadata.labels or {}),
                    annotations=dict(pod.metadata.annotations or {}),
                ),
                spec=copy.deepcopy(pod.spec),
            ),
        )
        try:
            self.client.create("podtemplates", journal, namespace=ns)
        except APIError as e:
            if e.code != 409:  # an orphan from a prior crash is fine
                rebalance_mon.DEFAULT.record_move("failed")
                return False
        try:
            self.client.evict(
                name,
                namespace=ns,
                grace_period_seconds=self.grace_period_seconds,
            )
        except APIError as e:
            if e.code != 404:  # gone already = evicted
                rebalance_mon.DEFAULT.record_move("failed")
                self._delete_journal(journal.metadata.name, ns)
                return False
        rebalance_mon.DEFAULT.record_move("evicted")
        try:
            self.client.record_event(
                pod,
                "RebalanceEvict",
                f"defragmentation move {m['from']} -> {m['to']} "
                f"(gain {m['gain']})",
                source="descheduler",
                namespace=ns,
            )
        except APIError:
            pass

        # THE crash window: the pod is evicted, the replacement does
        # not exist yet. Only the journal stands between a crash here
        # and a stranded pod — which is exactly what the chaos soak's
        # mid-defrag kill epoch asserts.
        faults.fire(faults.DESCHED_MOVE_CRASH, key)

        if not self._wait_gone(name, ns):
            # Terminating but not confirmed: leave the journal; the
            # recovery pass recreates once the store lets go.
            return True
        try:
            self.client.create(
                "pods", self._replacement(journal.template, m["to"]),
                namespace=ns,
            )
        except APIError:
            rebalance_mon.DEFAULT.record_move("failed")
            return True  # journal survives -> recovery will replay
        if not defer_bind:
            try:
                self.client.patch(
                    "pods",
                    name,
                    {"status": {"nominatedNodeName": m["to"]}},
                    namespace=ns,
                )
            except APIError:
                pass
        flightrecorder.DEFAULT.record_preemption(
            key,
            "rebalance_nominated",
            node=m["to"],
            reason=f"defrag move from {m['from']} (gain {m['gain']})",
        )
        self._delete_journal(journal.metadata.name, ns)
        return True

    def _commit_gang(self, done: List[dict]) -> None:
        """Atomically bind a gang group's recreated members at their
        planned destinations — the slice lands as one transaction (any
        conflict rejects the whole batch; the pods then re-pend pinned
        and the gang solver places them)."""
        ns = done[0]["namespace"]
        try:
            self.client.bind_bulk(
                [(m["name"], m["to"]) for m in done],
                namespace=ns,
                atomic=True,
            )
            rebalance_mon.DEFAULT.record_move("rebound", len(done))
            for m in done:
                # Bound by us: settle the nomination immediately.
                try:
                    self.client.patch(
                        "pods",
                        m["name"],
                        {
                            "metadata": {
                                "annotations": {REBALANCE_DEST_ANNOTATION: ""}
                            }
                        },
                        namespace=ns,
                    )
                except APIError:
                    pass
        except APIError:
            pass  # pods stay pinned+pending; the solver lands them

    # -- plumbing ----------------------------------------------------------

    def _replacement(self, template: PodTemplateSpec, dest: str) -> Pod:
        """The evicted pod's next incarnation: same name/labels, fresh
        uid (the server assigns one — bind-immutability-per-uid holds),
        unbound, pinned at the planned destination."""
        spec = copy.deepcopy(template.spec)
        spec.node_name = ""
        annotations = dict(template.metadata.annotations or {})
        if dest:
            annotations[REBALANCE_DEST_ANNOTATION] = dest
        return Pod(
            metadata=ObjectMeta(
                name=template.metadata.name,
                namespace=template.metadata.namespace or "default",
                labels=dict(template.metadata.labels or {}),
                annotations=annotations,
            ),
            spec=spec,
            status=PodStatus(phase="Pending"),
        )

    def _wait_gone(self, name: str, ns: str) -> bool:
        deadline = time.time() + self.wait_timeout_s
        while time.time() < deadline:
            try:
                self.client.get("pods", name, namespace=ns)
            except APIError as e:
                if e.code == 404:
                    return True
                return False
            time.sleep(0.05)
        return False

    def _delete_journal(self, name: str, ns: str) -> None:
        try:
            self.client.delete("podtemplates", name, namespace=ns)
        except APIError:
            pass
