"""ReplicationManager: keeps actual pod counts equal to RC replicas.

Reference: pkg/controller/replication_controller.go:98-384. The
expectation tracker prevents over-creation while watch events are in
flight (controller_utils.go RCExpectations): after issuing N creates we
wait to observe N adds before diffing again.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from kubernetes_tpu.client.cache import Informer
from kubernetes_tpu.models import labels as labelpkg
from kubernetes_tpu.models import serde
from kubernetes_tpu.models.objects import Pod, ReplicationController
from kubernetes_tpu.server.api import APIError
from kubernetes_tpu.utils import metrics

_SYNCS = metrics.DEFAULT.counter(
    "replication_controller_syncs_total", "RC sync passes", ("result",)
)


def _decode_rc(wire: dict) -> ReplicationController:
    return serde.from_wire(ReplicationController, wire)


def _decode_pod(wire: dict) -> Pod:
    return serde.from_wire(Pod, wire)


class _Expectations:
    """Per-RC add/del expectations (controller_utils.go)."""

    TIMEOUT = 30.0

    def __init__(self):
        self._lock = threading.Lock()
        self._exp: Dict[str, tuple] = {}  # key -> (adds, dels, stamp)

    def expect(self, key: str, adds: int, dels: int) -> None:
        with self._lock:
            self._exp[key] = (adds, dels, time.monotonic())

    def observe_add(self, key: str) -> None:
        with self._lock:
            if key in self._exp:
                a, d, t = self._exp[key]
                self._exp[key] = (max(0, a - 1), d, t)

    def observe_del(self, key: str) -> None:
        with self._lock:
            if key in self._exp:
                a, d, t = self._exp[key]
                self._exp[key] = (a, max(0, d - 1), t)

    def satisfied(self, key: str) -> bool:
        with self._lock:
            if key not in self._exp:
                return True
            a, d, t = self._exp[key]
            if a <= 0 and d <= 0:
                return True
            if time.monotonic() - t > self.TIMEOUT:
                return True  # expectations expire; resync will fix drift
            return False


class ReplicationManager:
    BURST_REPLICAS = 500  # reference: 500 (replication_controller.go:64)

    def __init__(self, client, sync_period: float = 5.0):
        self.client = client
        self.sync_period = sync_period
        self.expectations = _Expectations()
        self._dirty = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.rcs = Informer(
            client, "replicationcontrollers", decode=_decode_rc,
            on_add=lambda o: self._dirty.set(),
            on_update=lambda o: self._dirty.set(),
            on_delete=lambda o: self._dirty.set(),
        )
        self.pods = Informer(
            client, "pods", decode=_decode_pod,
            on_add=self._pod_added,
            on_delete=self._pod_deleted,
        )

    # -- watch handlers ----------------------------------------------

    def _rc_key_for_pod(self, pod: Pod) -> Optional[str]:
        for rc in self.rcs.store.list():
            if rc.metadata.namespace != pod.metadata.namespace:
                continue
            sel = rc.spec.selector
            if sel and labelpkg.selector_from_set(sel).matches(pod.metadata.labels):
                return f"{rc.metadata.namespace}/{rc.metadata.name}"
        return None

    def _pod_added(self, pod: Pod) -> None:
        key = self._rc_key_for_pod(pod)
        if key:
            self.expectations.observe_add(key)
        self._dirty.set()

    def _pod_deleted(self, pod: Pod) -> None:
        key = self._rc_key_for_pod(pod)
        if key:
            self.expectations.observe_del(key)
        self._dirty.set()

    # -- lifecycle ----------------------------------------------------

    def start(self) -> "ReplicationManager":
        self.rcs.start()
        self.pods.start()
        self.rcs.wait_for_sync()
        self.pods.wait_for_sync()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._dirty.set()
        self.rcs.stop()
        self.pods.stop()
        if self._thread:
            self._thread.join(timeout=3)

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._dirty.wait(timeout=self.sync_period)
            self._dirty.clear()
            if self._stop.is_set():
                return
            try:
                self.sync_all()
            except Exception:
                pass

    # -- reconciliation ----------------------------------------------

    def sync_all(self) -> None:
        # Per-RC error isolation: one broken RC must not starve the rest
        # (the reference syncs per queue key with individual handling).
        for rc in self.rcs.store.list():
            try:
                self.sync_rc(rc)
            except Exception:
                _SYNCS.inc(result="error")

    def _matching_pods(self, rc: ReplicationController) -> List[Pod]:
        sel = labelpkg.selector_from_set(rc.spec.selector)
        return [
            p
            for p in self.pods.store.list()
            if p.metadata.namespace == rc.metadata.namespace
            and sel.matches(p.metadata.labels)
            and p.status.phase not in ("Succeeded", "Failed")
        ]

    def sync_rc(self, rc: ReplicationController) -> None:
        """syncReplicationController (:351) + manageReplicas (:294)."""
        key = f"{rc.metadata.namespace}/{rc.metadata.name}"
        if not self.expectations.satisfied(key):
            return
        pods = self._matching_pods(rc)
        diff = len(pods) - rc.spec.replicas
        if diff < 0:
            count = min(-diff, self.BURST_REPLICAS)
            self.expectations.expect(key, adds=count, dels=0)
            for _ in range(count):
                if not self._create_pod(rc):
                    # Lower expectations by exactly the failed create so
                    # concurrent watch-observed adds still count
                    # (reference: rm.expectations.CreationObserved on
                    # failure, replication_controller.go:294+).
                    self.expectations.observe_add(key)
            _SYNCS.inc(result="scale_up")
        elif diff > 0:
            count = min(diff, self.BURST_REPLICAS)
            # Prefer killing unassigned/pending pods first (reference
            # sorts by activePods ordering).
            pods.sort(key=lambda p: (p.spec.node_name != "", p.status.phase == "Running"))
            victims = pods[:count]
            self.expectations.expect(key, adds=0, dels=len(victims))
            for p in victims:
                try:
                    self.client.delete(
                        "pods", p.metadata.name,
                        namespace=p.metadata.namespace or "default",
                    )
                except APIError:
                    self.expectations.observe_del(key)
            _SYNCS.inc(result="scale_down")
        else:
            _SYNCS.inc(result="in_sync")
        # Status writeback (:384) — guard on the value actually written,
        # else unchanged writes loop through the watch forever.
        if rc.status.replicas != len(pods):
            rc.status.replicas = len(pods)
            try:
                self.client.update_status(
                    "replicationcontrollers", rc,
                    namespace=rc.metadata.namespace or "default",
                )
            except APIError:
                pass

    def _create_pod(self, rc: ReplicationController) -> bool:
        tmpl = rc.spec.template
        if tmpl is None:
            return False
        pod = Pod()
        pod.metadata.generate_name = rc.metadata.name + "-"
        pod.metadata.namespace = rc.metadata.namespace or "default"
        pod.metadata.labels = dict(tmpl.metadata.labels or {})
        pod.spec = serde.from_wire(type(tmpl.spec), serde.to_wire(tmpl.spec))
        try:
            self.client.create("pods", pod, namespace=pod.metadata.namespace)
            return True
        except APIError:
            return False
