"""ReplicationManager: keeps actual pod counts equal to RC replicas.

Reference: pkg/controller/replication_controller.go:98-384. The
expectation tracker prevents over-creation while watch events are in
flight (controller_utils.go RCExpectations): after issuing N creates we
wait to observe N adds before diffing again.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional

from kubernetes_tpu.client.cache import Informer
from kubernetes_tpu.models import labels as labelpkg
from kubernetes_tpu.models import serde
from kubernetes_tpu.models.objects import Pod, ReplicationController
from kubernetes_tpu.server.api import APIError
from kubernetes_tpu.utils import metrics

_LOG = logging.getLogger("kubernetes_tpu.controllers.replication")

_SYNCS = metrics.DEFAULT.counter(
    "replication_controller_syncs_total", "RC sync passes", ("result",)
)


def _decode_rc(wire: dict) -> ReplicationController:
    return serde.from_wire(ReplicationController, wire)


def _decode_pod(wire: dict) -> Pod:
    return serde.from_wire(Pod, wire)


class _Expectations:
    """Per-RC add/del expectations (controller_utils.go)."""

    TIMEOUT = 30.0

    def __init__(self):
        self._lock = threading.Lock()
        self._exp: Dict[str, tuple] = {}  # key -> (adds, dels, stamp)

    def expect(self, key: str, adds: int, dels: int) -> None:
        with self._lock:
            self._exp[key] = (adds, dels, time.monotonic())

    def observe_add(self, key: str) -> None:
        with self._lock:
            if key in self._exp:
                a, d, t = self._exp[key]
                self._exp[key] = (max(0, a - 1), d, t)

    def observe_del(self, key: str) -> None:
        with self._lock:
            if key in self._exp:
                a, d, t = self._exp[key]
                self._exp[key] = (a, max(0, d - 1), t)

    def satisfied(self, key: str) -> bool:
        with self._lock:
            if key not in self._exp:
                return True
            a, d, t = self._exp[key]
            if a <= 0 and d <= 0:
                return True
            if time.monotonic() - t > self.TIMEOUT:
                return True  # expectations expire; resync will fix drift
            return False


class ReplicationManager:
    BURST_REPLICAS = 500  # reference: 500 (replication_controller.go:64)

    def __init__(self, client, sync_period: float = 5.0):
        self.client = client
        self.sync_period = sync_period
        self.expectations = _Expectations()
        self._rc_key_cache: Dict[tuple, Optional[str]] = {}
        self._dirty = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.rcs = Informer(
            client, "replicationcontrollers", decode=_decode_rc,
            on_add=self._rc_changed,
            on_update=self._rc_changed,
            on_delete=self._rc_changed,
        )
        self.pods = Informer(
            client, "pods", decode=_decode_pod,
            on_add=self._pod_added,
            on_delete=self._pod_deleted,
        )

    # -- watch handlers ----------------------------------------------

    def _rc_changed(self, _rc) -> None:
        """RC add/update/delete: invalidate the pod->RC memo BEFORE
        waking the sync loop. The memo can hold a stale None computed
        before a new matching RC appeared — pod events for that RC
        would then skip expectation observation until the 30s
        expectations timeout (slow convergence; ADVICE r5). The
        per-round clear in sync_all still runs; this closes the gap
        between an RC appearing and the next round."""
        self._rc_key_cache.clear()
        self._dirty.set()

    def _rc_key_for_pod(self, pod: Pod) -> Optional[str]:
        # Memoized by (namespace, label signature): this runs on the
        # reflector thread for EVERY pod event, and rebuilding one
        # Selector per RC per event is O(RCs) selector constructions x
        # 30k events at scale. Pods from one template share a
        # signature; sync_all (and _rc_changed) clear the cache so RC
        # churn converges within a sync period.
        labels = pod.metadata.labels or {}
        sig = (pod.metadata.namespace, frozenset(labels.items()))
        cache = self._rc_key_cache
        if sig in cache:
            return cache[sig]
        out = None
        for rc in self.rcs.store.list():
            if rc.metadata.namespace != pod.metadata.namespace:
                continue
            sel = rc.spec.selector
            if sel and labelpkg.selector_from_set(sel).matches(labels):
                out = f"{rc.metadata.namespace}/{rc.metadata.name}"
                break
        if len(cache) > 4096:
            cache.clear()
        cache[sig] = out
        return out

    def _pod_added(self, pod: Pod) -> None:
        key = self._rc_key_for_pod(pod)
        if key:
            self.expectations.observe_add(key)
        self._dirty.set()

    def _pod_deleted(self, pod: Pod) -> None:
        key = self._rc_key_for_pod(pod)
        if key:
            self.expectations.observe_del(key)
        self._dirty.set()

    # -- lifecycle ----------------------------------------------------

    def start(self) -> "ReplicationManager":
        self.rcs.start()
        self.pods.start()
        self.rcs.wait_for_sync()
        self.pods.wait_for_sync()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._dirty.set()
        self.rcs.stop()
        self.pods.stop()
        if self._thread:
            self._thread.join(timeout=3)
        pool = getattr(self, "_burst_pool", None)
        if pool is not None:
            pool.shutdown(wait=False)
            self._burst_pool = None

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._dirty.wait(timeout=self.sync_period)
            self._dirty.clear()
            if self._stop.is_set():
                return
            try:
                self.sync_all()
            except Exception:
                _LOG.exception("replication sync pass failed")

    # -- reconciliation ----------------------------------------------

    def sync_all(self) -> None:
        # ONE pass over the pod cache, memoized by label signature:
        # per-RC re-listing is O(pods x RCs) per round (3M selector
        # matches at 30k pods x 100 RCs — the controller's whole core
        # share at 1000-node scale). Pods from one template share a
        # label signature, so distinct match computations ~ #templates.
        self._rc_key_cache.clear()  # RC set may have changed
        rcs = self.rcs.store.list()
        if not rcs:
            return
        rc_sels = [
            (rc, labelpkg.selector_from_set(rc.spec.selector or {}), [])
            for rc in rcs
        ]
        sig_hits: Dict[tuple, List[int]] = {}
        for p in self.pods.store.list():
            if p.status.phase in ("Succeeded", "Failed"):
                continue
            labels = p.metadata.labels or {}
            sig = (p.metadata.namespace, frozenset(labels.items()))
            hits = sig_hits.get(sig)
            if hits is None:
                hits = [
                    i
                    for i, (rc, sel, _m) in enumerate(rc_sels)
                    if rc.metadata.namespace == p.metadata.namespace
                    and not sel.empty()
                    and sel.matches(labels)
                ]
                sig_hits[sig] = hits
            for i in hits:
                rc_sels[i][2].append(p)
        # Per-RC error isolation: one broken RC must not starve the rest
        # (the reference syncs per queue key with individual handling).
        for rc, _sel, matched in rc_sels:
            try:
                self.sync_rc(rc, matched)
            except Exception:
                _LOG.exception(
                    "sync of replicationcontroller %s/%s failed",
                    rc.metadata.namespace, rc.metadata.name,
                )
                _SYNCS.inc(result="error")

    def _matching_pods(self, rc: ReplicationController) -> List[Pod]:
        sel = labelpkg.selector_from_set(rc.spec.selector)
        return [
            p
            for p in self.pods.store.list()
            if p.metadata.namespace == rc.metadata.namespace
            and sel.matches(p.metadata.labels)
            and p.status.phase not in ("Succeeded", "Failed")
        ]

    def sync_rc(
        self, rc: ReplicationController, pods: Optional[List[Pod]] = None
    ) -> None:
        """syncReplicationController (:351) + manageReplicas (:294).
        `pods` = this RC's active pods when the caller (sync_all)
        already computed them; None recomputes."""
        key = f"{rc.metadata.namespace}/{rc.metadata.name}"
        if not self.expectations.satisfied(key):
            return
        if pods is None:
            pods = self._matching_pods(rc)
        else:
            pods = list(pods)
        diff = len(pods) - rc.spec.replicas
        if diff < 0:
            count = min(-diff, self.BURST_REPLICAS)
            self.expectations.expect(key, adds=count, dels=0)
            # Concurrent burst, like the reference's per-create
            # goroutines (manageReplicas fires `go rm.createPods` for
            # the whole diff): a serial loop caps creation at
            # 1/apiserver-round-trip — under load at 1000 nodes that
            # was ~16 pods/s for a 30k-pod fan-out.
            for ok in self._pool().map(
                lambda _i: self._create_pod(rc), range(count)
            ):
                if not ok:
                    # Lower expectations by exactly the failed create so
                    # concurrent watch-observed adds still count
                    # (reference: rm.expectations.CreationObserved on
                    # failure, replication_controller.go:294+).
                    self.expectations.observe_add(key)
            _SYNCS.inc(result="scale_up")
        elif diff > 0:
            count = min(diff, self.BURST_REPLICAS)
            # Prefer killing unassigned/pending pods first (reference
            # sorts by activePods ordering).
            pods.sort(key=lambda p: (p.spec.node_name != "", p.status.phase == "Running"))
            victims = pods[:count]
            self.expectations.expect(key, adds=0, dels=len(victims))
            for p in victims:
                try:
                    self.client.delete(
                        "pods", p.metadata.name,
                        namespace=p.metadata.namespace or "default",
                    )
                except APIError:
                    self.expectations.observe_del(key)
            _SYNCS.inc(result="scale_down")
        else:
            _SYNCS.inc(result="in_sync")
        # Status writeback (:384) — guard on the value actually written,
        # else unchanged writes loop through the watch forever.
        if rc.status.replicas != len(pods):
            rc.status.replicas = len(pods)
            try:
                self.client.update_status(
                    "replicationcontrollers", rc,
                    namespace=rc.metadata.namespace or "default",
                )
            except APIError:
                pass

    def _pool(self):
        """Shared burst executor (the goroutine analog, bounded)."""
        if getattr(self, "_burst_pool", None) is None:
            from concurrent.futures import ThreadPoolExecutor

            self._burst_pool = ThreadPoolExecutor(
                max_workers=8, thread_name_prefix="rc-burst"
            )
        return self._burst_pool

    def _create_pod(self, rc: ReplicationController) -> bool:
        tmpl = rc.spec.template
        if tmpl is None:
            return False
        pod = Pod()
        pod.metadata.generate_name = rc.metadata.name + "-"
        pod.metadata.namespace = rc.metadata.namespace or "default"
        pod.metadata.labels = dict(tmpl.metadata.labels or {})
        pod.spec = serde.from_wire(type(tmpl.spec), serde.to_wire(tmpl.spec))
        try:
            self.client.create("pods", pod, namespace=pod.metadata.namespace)
            return True
        except APIError:
            return False
