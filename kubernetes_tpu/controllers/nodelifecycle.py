"""NodeLifecycleController: detect dead nodes, evict their pods.

Reference: pkg/cloudprovider/nodecontroller/nodecontroller.go:186-341 —
monitor NodeStatus heartbeats; after a grace period mark the node
NotReady (ConditionUnknown in the reference); evict its pods after the
eviction timeout so the replication controller can recreate them
elsewhere. Eviction is rate-limited.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, Optional

from kubernetes_tpu.client.cache import Informer
from kubernetes_tpu.models import serde
from kubernetes_tpu.models.objects import Node, Pod, now_iso
from kubernetes_tpu.server.api import APIError
from kubernetes_tpu.utils.ratelimit import TokenBucket

_LOG = logging.getLogger("kubernetes_tpu.controllers.nodelifecycle")


def _decode_node(wire: dict) -> Node:
    return serde.from_wire(Node, wire)


def _decode_pod(wire: dict) -> Pod:
    return serde.from_wire(Pod, wire)


class NodeLifecycleController:
    def __init__(
        self,
        client,
        monitor_period: float = 2.0,
        # Reference defaults, deliberately: --node-monitor-grace-period
        # defaults to 40s ("must be N times more than the kubelet's
        # status update frequency") and --pod-eviction-timeout to 5min
        # (cmd/kube-controller-manager/app/controllermanager.go:106,140).
        # Round 4 originally shipped 8s/4s — 5x/75x tighter — and at
        # 100 kubelets a heartbeat delayed by the pod-creation burst
        # read as node death, so mass eviction landed exactly when the
        # control plane was busiest and the recreate/rebind storm fed
        # itself. Failure-drill tests pass short values explicitly.
        grace_period: float = 40.0,
        eviction_timeout: float = 120.0,
        eviction_qps: float = 10.0,
    ):
        self.client = client
        self.monitor_period = monitor_period
        self.grace_period = grace_period
        self.eviction_timeout = eviction_timeout
        self.eviction_limiter = TokenBucket(eviction_qps, burst=20)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # node -> monotonic time of last observed heartbeat change.
        self._last_seen: Dict[str, float] = {}
        self._last_heartbeat: Dict[str, str] = {}
        self._not_ready_since: Dict[str, float] = {}
        self.nodes = Informer(client, "nodes", decode=_decode_node)
        self.pods = Informer(client, "pods", decode=_decode_pod)

    def start(self) -> "NodeLifecycleController":
        self.nodes.start()
        self.pods.start()
        self.nodes.wait_for_sync()
        self.pods.wait_for_sync()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self.nodes.stop()
        self.pods.stop()
        if self._thread:
            self._thread.join(timeout=3)

    def _loop(self) -> None:
        while not self._stop.wait(self.monitor_period):
            try:
                self.monitor()
            except Exception:
                _LOG.exception("node lifecycle monitor pass failed")

    # -- monitoring ---------------------------------------------------

    @staticmethod
    def _heartbeat_of(node: Node) -> str:
        for c in node.status.conditions:
            if c.type == "Ready":
                return c.last_heartbeat_time
        return ""

    @staticmethod
    def _is_ready(node: Node) -> bool:
        for c in node.status.conditions:
            if c.type == "Ready":
                return c.status == "True"
        return False

    def monitor(self) -> None:
        now = time.monotonic()
        for node in self.nodes.store.list():
            name = node.metadata.name
            hb = self._heartbeat_of(node)
            if self._last_heartbeat.get(name) != hb:
                self._last_heartbeat[name] = hb
                self._last_seen[name] = now
                continue
            last = self._last_seen.setdefault(name, now)
            if now - last < self.grace_period:
                continue
            # Heartbeat stale past the grace period.
            if self._is_ready(node):
                self._mark_not_ready(node)
            since = self._not_ready_since.setdefault(name, now)
            if now - since >= self.eviction_timeout:
                self._evict_pods(name)
        # Reset eviction clocks for nodes that recovered.
        for node in self.nodes.store.list():
            name = node.metadata.name
            if self._is_ready(node) and (
                time.monotonic() - self._last_seen.get(name, 0)
                < self.grace_period
            ):
                self._not_ready_since.pop(name, None)

    def _mark_not_ready(self, node: Node) -> None:
        for c in node.status.conditions:
            if c.type == "Ready":
                c.status = "Unknown"
                c.reason = "NodeStatusUnknown"
                c.message = "Kubelet stopped posting node status"
                c.last_transition_time = now_iso()
        try:
            self.client.update_status("nodes", node)
            self.client.record_event(
                node, "NodeNotReady", f"Node {node.metadata.name} stopped heartbeating",
                source="node-controller",
            )
        except APIError:
            pass

    def _evict_pods(self, node_name: str) -> None:
        """deletePods (nodecontroller.go:341): remove pods so the RC
        manager recreates them on live nodes."""
        for pod in self.pods.store.list():
            if pod.spec.node_name != node_name:
                continue
            if not self.eviction_limiter.try_accept():
                return  # rate limited: resume next tick
            try:
                self.client.delete(
                    "pods", pod.metadata.name,
                    namespace=pod.metadata.namespace or "default",
                )
                self.client.record_event(
                    pod, "NodeControllerEviction",
                    f"Deleting pod from unresponsive node {node_name}",
                    source="node-controller",
                )
            except APIError:
                pass
