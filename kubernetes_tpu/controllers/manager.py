"""ControllerManager: launches all control loops against one client.

Reference: cmd/kube-controller-manager/app/controllermanager.go:201-263.
"""

from __future__ import annotations

from typing import List, Optional

from kubernetes_tpu.controllers.endpoints import EndpointsController
from kubernetes_tpu.controllers.nodelifecycle import NodeLifecycleController
from kubernetes_tpu.controllers.replication import ReplicationManager


class ControllerManager:
    def __init__(
        self,
        client,
        enable_replication: bool = True,
        enable_endpoints: bool = True,
        enable_node_lifecycle: bool = True,
        node_grace_period: float = 8.0,
        node_eviction_timeout: float = 4.0,
    ):
        self.controllers: List = []
        if enable_replication:
            self.replication = ReplicationManager(client)
            self.controllers.append(self.replication)
        if enable_endpoints:
            self.endpoints = EndpointsController(client)
            self.controllers.append(self.endpoints)
        if enable_node_lifecycle:
            self.node_lifecycle = NodeLifecycleController(
                client,
                grace_period=node_grace_period,
                eviction_timeout=node_eviction_timeout,
            )
            self.controllers.append(self.node_lifecycle)

    def start(self) -> "ControllerManager":
        for c in self.controllers:
            c.start()
        return self

    def stop(self) -> None:
        for c in self.controllers:
            c.stop()
