"""ControllerManager: launches all control loops against one client.

Reference: cmd/kube-controller-manager/app/controllermanager.go:201-263.
"""

from __future__ import annotations

from typing import List, Optional

from kubernetes_tpu.controllers.endpoints import EndpointsController
from kubernetes_tpu.controllers.gangs import GangController
from kubernetes_tpu.controllers.namespace import NamespaceManager
from kubernetes_tpu.controllers.nodelifecycle import NodeLifecycleController
from kubernetes_tpu.controllers.replication import ReplicationManager
from kubernetes_tpu.controllers.resourcequota import ResourceQuotaManager
from kubernetes_tpu.controllers.serviceaccounts import (
    ServiceAccountsController,
    TokenController,
)
from kubernetes_tpu.controllers.pvrecycler import PersistentVolumeRecycler
from kubernetes_tpu.controllers.volumeclaimbinder import (
    PersistentVolumeClaimBinder,
)


class ControllerManager:
    def __init__(
        self,
        client,
        enable_replication: bool = True,
        enable_endpoints: bool = True,
        enable_node_lifecycle: bool = True,
        enable_namespace: bool = True,
        enable_resource_quota: bool = True,
        enable_service_accounts: bool = True,
        enable_pv_binder: bool = True,
        enable_gangs: bool = True,
        # Rebalancing plane (PR 17): the descheduler actively EVICTS
        # bound pods, so it is strictly opt-in; the autoscaler only
        # runs when handed a pool provider to resize.
        enable_descheduler: bool = False,
        descheduler_frag_threshold: float = 0.5,
        autoscaler_pool=None,
        # Reference defaults (see nodelifecycle.py): grace 40s,
        # eviction 5min there — 120s here keeps recovery drills sane.
        node_grace_period: float = 40.0,
        node_eviction_timeout: float = 120.0,
        sa_token_manager=None,
        cloud_provider=None,
    ):
        self.controllers: List = []
        self.running = False  # live health signal (componentstatuses)
        if cloud_provider is not None:
            from kubernetes_tpu.controllers.cloudnodes import CloudNodeController
            from kubernetes_tpu.controllers.routes import RouteController
            from kubernetes_tpu.controllers.servicelb import ServiceController

            self.cloud_nodes = CloudNodeController(client, cloud_provider)
            self.controllers.append(self.cloud_nodes)
            if cloud_provider.load_balancer() is not None:
                self.service_lb = ServiceController(client, cloud_provider)
                self.controllers.append(self.service_lb)
            if cloud_provider.routes() is not None:
                self.route_controller = RouteController(client, cloud_provider)
                self.controllers.append(self.route_controller)
        if enable_replication:
            self.replication = ReplicationManager(client)
            self.controllers.append(self.replication)
        if enable_endpoints:
            self.endpoints = EndpointsController(client)
            self.controllers.append(self.endpoints)
        if enable_node_lifecycle:
            self.node_lifecycle = NodeLifecycleController(
                client,
                grace_period=node_grace_period,
                eviction_timeout=node_eviction_timeout,
            )
            self.controllers.append(self.node_lifecycle)
        if enable_namespace:
            self.namespace = NamespaceManager(client)
            self.controllers.append(self.namespace)
        if enable_resource_quota:
            self.resource_quota = ResourceQuotaManager(client)
            self.controllers.append(self.resource_quota)
        if enable_service_accounts:
            self.service_accounts = ServiceAccountsController(client)
            self.controllers.append(self.service_accounts)
            if sa_token_manager is not None:
                self.tokens = TokenController(client, sa_token_manager)
                self.controllers.append(self.tokens)
        if enable_gangs:
            # PodGroup lifecycle: status reconcile + pending-gang aging
            # (events, Unschedulable marking) for the gang scheduler.
            # Shares the replication manager's typed pods informer when
            # present: one all-pods watch + decode per process, not two.
            self.gangs = GangController(
                client,
                pods_informer=getattr(
                    getattr(self, "replication", None), "pods", None
                ),
            )
            self.controllers.append(self.gangs)
        if enable_descheduler or autoscaler_pool is not None:
            from kubernetes_tpu.controllers.descheduler import Descheduler

            self.descheduler = Descheduler(
                client, frag_threshold=descheduler_frag_threshold
            )
            if enable_descheduler:
                self.controllers.append(self.descheduler)
            if autoscaler_pool is not None:
                from kubernetes_tpu.controllers.autoscaler import Autoscaler

                self.autoscaler = Autoscaler(
                    client, autoscaler_pool, descheduler=self.descheduler
                )
                self.controllers.append(self.autoscaler)
        if enable_pv_binder:
            self.pv_binder = PersistentVolumeClaimBinder(client)
            self.controllers.append(self.pv_binder)
            # The binder's other half: Released+Recycle -> scrub ->
            # Available (persistent_volume_recycler.go rides alongside
            # the claim binder in the reference controller-manager).
            self.pv_recycler = PersistentVolumeRecycler(client)
            self.controllers.append(self.pv_recycler)

    def start(self) -> "ControllerManager":
        for c in self.controllers:
            c.start()
        self.running = True
        return self

    def stop(self) -> None:
        self.running = False
        for c in self.controllers:
            c.stop()
