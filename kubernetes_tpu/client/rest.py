"""Typed REST client with pluggable transport.

Reference: pkg/client/client.go + request.go. Two transports:

- LocalTransport: direct calls into an in-process APIServer (the
  reference's cmd/integration wires components the same way).
- HTTPTransport: real HTTP to an APIHTTPServer, with streaming watch
  over chunked newline-delimited JSON.

Both yield identical semantics, so every component runs in-process for
tests and over the wire in deployment.
"""

from __future__ import annotations

import http.client
import json
import random
import ssl
import threading
import time
from typing import Any, Dict, Iterator, List, Optional, Tuple
from urllib.parse import urlencode, urlparse

from kubernetes_tpu.models import serde
from kubernetes_tpu.server.api import APIError, APIServer
from kubernetes_tpu.server.registry import RESOURCES
from kubernetes_tpu.store.watch import Event
from kubernetes_tpu.utils import faults, tracing
from kubernetes_tpu.utils.ratelimit import TokenBucket

#: Failures that mean a pooled keep-alive connection went stale
#: (server restart / idle close) rather than the request being bad.
_STALE_ERRORS = (
    http.client.BadStatusLine,
    http.client.CannotSendRequest,
    ConnectionError,
    BrokenPipeError,
    ssl.SSLError,
)

#: Verbs auto-replayed when a reused connection dies mid-read — the
#: idempotent set Go net/http and urllib3 replay. NOTE idempotent ≠
#: invisible: if the server applied the first attempt before dying, a
#: replayed CAS PUT (stale resourceVersion) surfaces 409 and a
#: replayed DELETE 404 — both on the caller's own success. Controllers
#: already treat those as benign re-read signals, which IS the
#: reconciliation. POST is excluded because its failure mode is worse:
#: a double-applied create, or a 409 the caller can't distinguish from
#: a genuine name collision.
_IDEMPOTENT_VERBS = frozenset({"GET", "HEAD", "PUT", "DELETE"})

#: 5xx codes that mean "the server (or something in front of it) is
#: transiently unavailable" — safe to retry on idempotent verbs. 500
#: is excluded: an internal error usually reproduces, and hammering it
#: just multiplies load on a struggling server.
_TRANSIENT_5XX = frozenset({502, 503, 504})

#: Jitter source for retry backoff — module-level and seeded (the
#: Summary-reservoir precedent from PR 1) so fault-injection tests
#: replay identical schedules.
_RETRY_RNG = random.Random(0x5EED)


class _ReplayStale(Exception):
    """Internal: a REUSED keep-alive connection went stale before any
    response byte — replay immediately on a fresh connection. Free
    (never counts against the transient-failure retry budget): the
    request provably never reached a live server."""


class UnknownOutcomeError(ConnectionError):
    """A non-idempotent request's connection died after send, before
    any response byte: the server may or may not have applied it.
    Callers should re-read the resource to reconcile rather than
    blindly retry (re-creating could 409 on their own success)."""

    def __init__(self, verb: str, path: str):
        super().__init__(
            f"{verb} {path}: connection lost before response; "
            "outcome unknown — reconcile by reading current state"
        )
        self.verb = verb
        self.path = path


class Transport:
    def request(self, verb: str, path_parts: tuple, query: dict, body: Optional[dict]):
        raise NotImplementedError

    def watch(
        self, resource: str, namespace: str, since: int, lsel: str, fsel: str
    ):
        raise NotImplementedError


class LocalTransport(Transport):
    def __init__(self, api: APIServer):
        self.api = api

    def request(self, verb, op, args, body=None, patch_type=None):
        # In-process "request span": the caller's trace context flows
        # straight through (same thread), so this is the analog of the
        # HTTP transport's X-Trace-Id hop. No-op without an active
        # trace.
        with tracing.span(f"api.{op}"):
            fn = getattr(self.api, op)
            if patch_type is not None:
                return fn(*args, body, patch_type=patch_type)
            if body is not None:
                return fn(*args, body)
            return fn(*args)

    def watch(self, resource, namespace, since, lsel, fsel):
        return self.api.watch(
            resource, namespace, since=since, label_selector=lsel, field_selector=fsel
        )


class _HTTPWatchStream:
    """Iterates chunked watch frames from an HTTP response.

    A reader thread does blocking readline()s and feeds a queue, so
    next(timeout) never sets socket timeouts — a timed-out wait cannot
    lose a partially-read frame (buffered readers drop consumed bytes
    when a raw read times out mid-line).
    """

    def __init__(self, conn: http.client.HTTPConnection, resp):
        import queue

        self._conn = conn
        self._resp = resp
        self._closed = False
        self._q: "queue.Queue[Optional[Event]]" = queue.Queue()
        self._thread = threading.Thread(target=self._read_loop, daemon=True)
        self._thread.start()

    def _read_loop(self) -> None:
        try:
            while True:
                line = self._resp.readline()
                if not line:
                    break
                try:
                    frame = json.loads(line)
                except json.JSONDecodeError:
                    break  # corrupt frame: drop the watch, caller re-lists
                obj = frame.get("object", {})
                version = int(
                    obj.get("metadata", {}).get("resourceVersion", "0") or "0"
                )
                self._q.put(Event(frame.get("type", "ERROR"), obj, version))
        except OSError:
            pass
        finally:
            self._closed = True
            try:
                self._conn.close()
            except Exception:
                pass
            self._q.put(None)

    def next(self, timeout: Optional[float] = None) -> Optional[Event]:
        import queue

        if self._closed and self._q.empty():
            return None
        try:
            ev = self._q.get(timeout=timeout)
        except queue.Empty:
            return None
        return ev

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            # Unblock the reader thread by shutting the raw socket; the
            # thread then closes the connection itself. Calling
            # conn.close() here would deadlock on the buffered reader's
            # lock, which the blocked readline() holds.
            import socket as _socket

            try:
                if self._conn.sock is not None:
                    self._conn.sock.shutdown(_socket.SHUT_RDWR)
            except OSError:
                pass

    @property
    def closed(self) -> bool:
        return self._closed

    def __iter__(self) -> Iterator[Event]:
        while True:
            ev = self.next()
            if ev is None:
                return
            yield ev


class HTTPTransport(Transport):
    def __init__(
        self,
        base_url,
        timeout: float = 30.0,
        headers: Optional[Dict[str, str]] = None,
        ssl_context=None,
        serialize: bool = False,
        max_retries: int = 3,
    ):
        # base_url: one URL, or a list of them (the HA control plane's
        # N stateless apiservers). Requests pin to one endpoint until
        # it fails transiently — then _rotate() advances to the next
        # replica INSIDE the existing retry loop, so a leader death or
        # a replica restart costs one backoff, not an outage.
        urls = [base_url] if isinstance(base_url, str) else list(base_url)
        if not urls:
            raise ValueError("HTTPTransport needs at least one endpoint")
        scheme = ""
        self.endpoints: List[Tuple[str, int]] = []
        for raw in urls:
            u = urlparse(raw)
            scheme = scheme or u.scheme
            self.endpoints.append(
                (
                    u.hostname or "127.0.0.1",
                    u.port or (443 if u.scheme == "https" else 80),
                )
            )
        self._ep_lock = threading.Lock()
        self._ep_idx = 0
        # Endpoint generation: bumped by _rotate(); pooled keep-alive
        # connections stamp the generation they dialed under, so every
        # thread (not just the one that saw the failure) re-dials the
        # new endpoint on its next request instead of keeping a socket
        # to the sick one.
        self._ep_gen = 0
        self.timeout = timeout
        # Static per-request headers (kubeconfig bearer/basic auth).
        self.headers = dict(headers or {})
        # TLS: an https:// base_url (or explicit context) switches to
        # HTTPSConnection; pass a context carrying a client cert/key
        # for x509 authentication against the apiserver.
        self.ssl_context = ssl_context
        if scheme == "https" and ssl_context is None:
            self.ssl_context = ssl.create_default_context()
        # Keep-alive: one persistent connection per thread. A fresh
        # TCP connection per request cost ~10x on CRUD throughput
        # (TCP_NODELAY on both ends matters just as much — Nagle +
        # delayed ACK stall keep-alive round trips ~40ms each).
        self._local = threading.local()
        # serialize=True: ONE shared connection, requests serialized
        # behind a lock — the Go client's few-multiplexed-connections
        # shape. A daemon with several worker threads (kubelet:
        # heartbeat + sync workers + resync) otherwise opens one
        # connection PER THREAD, and at 100 daemons the apiserver's
        # thread-per-connection tier drowns in its own thread count.
        # Watches are unaffected (they always own a dedicated socket).
        self._lock = threading.Lock() if serialize else None
        self._shared_conn = None
        # Transient-failure budget: connection errors / transient 5xx
        # on IDEMPOTENT verbs retry up to this many times with capped,
        # jittered exponential backoff (see _retry_backoff). 0 restores
        # the historical fail-fast behavior. Distinct from the free
        # stale-keep-alive replay, which never counts.
        self.max_retries = max_retries

    @property
    def host(self) -> str:
        return self.endpoints[self._ep_idx][0]

    @property
    def port(self) -> int:
        return self.endpoints[self._ep_idx][1]

    def _rotate(self) -> None:
        """Advance to the next endpoint after a transient failure and
        invalidate every pooled connection (generation bump). With one
        endpoint this is just the pool discard the retry already did."""
        with self._ep_lock:
            if len(self.endpoints) > 1:
                self._ep_idx = (self._ep_idx + 1) % len(self.endpoints)
            self._ep_gen += 1
        self._discard()

    def _connect(self, timeout=None) -> http.client.HTTPConnection:
        if self.ssl_context is not None:
            conn = http.client.HTTPSConnection(
                self.host, self.port, timeout=timeout, context=self.ssl_context
            )
        else:
            conn = http.client.HTTPConnection(
                self.host, self.port, timeout=timeout
            )
        conn.connect()
        try:
            import socket as _socket

            raw = getattr(conn, "sock", None)
            if raw is not None:
                raw.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
        except OSError:
            pass
        return conn

    def _pooled(self) -> tuple:
        """(connection, reused) for this thread (or the shared one).
        A pooled connection whose endpoint generation is stale (the
        transport rotated since it dialed) is discarded and re-dialed
        against the current endpoint."""
        gen = self._ep_gen
        if self._lock is not None:
            if (
                self._shared_conn is not None
                and getattr(self, "_shared_gen", -1) == gen
            ):
                return self._shared_conn, True
            if self._shared_conn is not None:
                try:
                    self._shared_conn.close()
                except Exception:
                    pass
            self._shared_conn = self._connect(timeout=self.timeout)
            self._shared_gen = gen
            return self._shared_conn, False
        conn = getattr(self._local, "conn", None)
        if conn is not None and getattr(self._local, "gen", -1) == gen:
            return conn, True
        if conn is not None:
            try:
                conn.close()
            except Exception:
                pass
        conn = self._connect(timeout=self.timeout)
        self._local.conn = conn
        self._local.gen = gen
        return conn, False

    def _discard(self) -> None:
        if self._lock is not None:
            conn, self._shared_conn = self._shared_conn, None
        else:
            conn = getattr(self._local, "conn", None)
            self._local.conn = None
        if conn is not None:
            try:
                conn.close()
            except Exception:
                pass

    # -- path construction mirroring the server's router --------------

    @staticmethod
    def _collection_path(resource: str, namespace: str) -> str:
        info = RESOURCES[resource]
        if info.namespaced and namespace:
            return f"/api/v1/namespaces/{namespace}/{info.name}"
        return f"/api/v1/{info.name}"

    def _do(
        self,
        verb: str,
        path: str,
        query: dict = None,
        body: dict = None,
        raw: bool = False,
        content_type: str = "application/json",
    ):
        """One request over the thread's keep-alive connection.
        raw=True returns the response text verbatim (pod logs);
        otherwise the JSON-decoded body.

        Stale-keep-alive handling: a REUSED connection that fails
        while SENDING retries once on a fresh connection for any verb
        (bytes can land in the kernel buffer of a half-closed socket,
        so most stale failures actually surface at the read). At the
        READ, RemoteDisconnected (a clean close with zero response
        bytes, the standard stale-keep-alive signal) retries only
        idempotent verbs (GET/HEAD/PUT/DELETE) — matching urllib3 and
        Go net/http, which never auto-replay a POST here, because the
        server may have executed the mutation and died before writing
        the response; a silent replay would double-apply (a create
        that actually succeeded would surface a spurious 409). POST
        raises UnknownOutcomeError so callers can reconcile. Other
        read failures retry only GETs.

        A FRESH connection's failure is a real outage — and so is a
        transient 5xx (502/503/504) from something restarting. Both
        now retry idempotent verbs up to ``max_retries`` times with
        capped, jittered exponential backoff (_retry_backoff) before
        propagating; non-idempotent verbs still fail fast (a replayed
        POST could double-apply)."""
        if self._lock is not None:
            with self._lock:
                return self._do_locked(
                    verb, path, query, body, raw, content_type
                )
        # serialize=False: there IS no serial lock — per-call sockets,
        # nothing shared to guard; the _locked suffix means "under the
        # serial lock when one exists".  # ktlint: disable=KTSAN02
        return self._do_locked(verb, path, query, body, raw, content_type)

    def _retry_backoff(self, attempt: int) -> None:
        """Capped, jittered exponential wait before transient-failure
        retry attempt `attempt` (1-based). Bounded by construction —
        base 50ms doubling, 1s cap, max_retries attempts — so the total
        added wait honors the same "no unbounded stall" contract KT004
        enforces on the socket timeouts."""
        delay = min(0.05 * (2 ** (attempt - 1)), 1.0)
        time.sleep(delay * (0.5 + 0.5 * _RETRY_RNG.random()))

    def _do_locked(
        self,
        verb: str,
        path: str,
        query: dict = None,
        body: dict = None,
        raw: bool = False,
        content_type: str = "application/json",
    ):
        if query:
            path = path + "?" + urlencode({k: v for k, v in query.items() if v})
        payload = json.dumps(body).encode() if body is not None else None
        headers = dict(self.headers)
        if payload:
            headers["Content-Type"] = content_type
        # Dapper hop: stamp the active trace id so the apiserver's
        # handling of this request records under the same trace.
        tid = tracing.current_trace_id()
        if tid:
            headers[tracing.TRACE_HEADER] = tid
        attempts = 0
        while True:
            try:
                if faults.enabled():
                    # Chaos seams (client/chaos.py's policy transport
                    # wraps whole transports; these sites sit INSIDE
                    # the retry loop so injected resets/5xx exercise
                    # the same recovery a real outage would).
                    faults.fire(faults.HTTP_DELAY, path)
                    faults.fire(faults.HTTP_RESET, path)
                    faults.fire(faults.HTTP_5XX, path)
                return self._attempt_locked(verb, path, payload, headers, raw)
            except _ReplayStale:
                continue  # stale keep-alive: free replay, no budget
            except APIError as e:
                if (
                    e.code in _TRANSIENT_5XX
                    and verb in _IDEMPOTENT_VERBS
                    and attempts < self.max_retries
                ):
                    attempts += 1
                    # This endpoint answered but is sick — try the
                    # next replica (no-op rotation when there is one).
                    self._rotate()
                    self._retry_backoff(attempts)
                    continue
                raise
            except _STALE_ERRORS:
                # Fresh-connection/transport failure (a real outage,
                # not a stale pool entry). UnknownOutcomeError is a
                # ConnectionError too, but only non-idempotent verbs
                # raise it — the verb check below re-raises it.
                if verb in _IDEMPOTENT_VERBS and attempts < self.max_retries:
                    attempts += 1
                    self._rotate()
                    self._retry_backoff(attempts)
                    continue
                raise

    def _attempt_locked(self, verb, path, payload, headers, raw):
        """One request attempt over the pooled connection. Raises
        _ReplayStale when a REUSED connection proved stale in a way
        that is safe to replay for this verb; every other failure
        propagates for _do_locked's transient-retry policy."""
        conn, reused = self._pooled()
        try:
            conn.request(verb, path, body=payload, headers=headers)
        except _STALE_ERRORS:
            self._discard()
            if reused:
                raise _ReplayStale()  # request never left: any verb
            raise
        except Exception:
            self._discard()
            raise
        try:
            resp = conn.getresponse()
            raw_body = resp.read()
        except http.client.RemoteDisconnected as e:
            self._discard()
            if reused and verb in _IDEMPOTENT_VERBS:
                raise _ReplayStale()  # clean close before any response bytes
            if reused:
                # POST/PATCH on a stale connection: the server may
                # have applied the mutation before dying. Don't
                # replay; tell the caller the outcome is unknown.
                raise UnknownOutcomeError(verb, path) from e
            raise
        except _STALE_ERRORS:
            self._discard()
            if reused and verb == "GET":
                raise _ReplayStale()
            raise
        except Exception:
            self._discard()
            raise
        if resp.will_close:
            self._discard()
        if resp.status >= 400:
            try:
                data = json.loads(raw_body or b"{}")
            except json.JSONDecodeError:
                data = {}
            raise APIError(
                data.get("code", resp.status),
                data.get("reason", "Unknown"),
                data.get("message", f"HTTP {resp.status}"),
            )
        if raw:
            return raw_body.decode(errors="replace")
        return json.loads(raw_body or b"{}")

    def get_json(self, path: str, query: Optional[Dict[str, str]] = None):
        """Public raw GET for non-/api surfaces the typed verbs don't
        model (debug endpoints, /metrics-adjacent JSON). Same pooled
        connection, auth headers, and retry semantics as every other
        request."""
        return self._do("GET", path, query=query)

    def get_text(
        self, path: str, query: Optional[Dict[str, str]] = None
    ) -> str:
        """get_json's text/plain sibling (`/debug/profile`, /metrics):
        the response body verbatim, same connection/auth/retry."""
        return self._do("GET", path, query=query, raw=True)

    def request(self, verb, op, args, body=None, patch_type=None):
        if op == "create":
            resource, namespace = args
            return self._do("POST", self._collection_path(resource, namespace), body=body)
        if op == "get":
            resource, namespace, name = args
            return self._do("GET", self._collection_path(resource, namespace) + f"/{name}")
        if op == "list":
            resource, namespace, lsel, fsel = args
            return self._do(
                "GET",
                self._collection_path(resource, namespace),
                query={"labelSelector": lsel, "fieldSelector": fsel},
            )
        if op == "update":
            resource, namespace, name = args
            return self._do(
                "PUT", self._collection_path(resource, namespace) + f"/{name}", body=body
            )
        if op == "update_status":
            resource, namespace, name = args
            return self._do(
                "PUT",
                self._collection_path(resource, namespace) + f"/{name}/status",
                body=body,
            )
        if op == "delete":
            resource, namespace, name = args[:3]
            grace = args[3] if len(args) > 3 else None
            return self._do(
                "DELETE",
                self._collection_path(resource, namespace) + f"/{name}",
                query=(
                    {"gracePeriodSeconds": str(int(grace))}
                    if grace is not None
                    else None
                ),
            )
        if op == "evict_pod":
            namespace, name = args
            return self._do(
                "POST",
                self._collection_path("pods", namespace or "default")
                + f"/{name}/eviction",
                body=body,
            )
        if op == "patch":
            resource, namespace, name = args
            ctype = {
                "json": "application/json-patch+json",
                "strategic": "application/strategic-merge-patch+json",
                "merge": "application/merge-patch+json",
            }.get(patch_type or "merge")
            return self._do(
                "PATCH",
                self._collection_path(resource, namespace) + f"/{name}",
                body=body,
                content_type=ctype,
            )
        if op == "bind":
            (namespace,) = args
            return self._do(
                "POST", f"/api/v1/namespaces/{namespace or 'default'}/bindings", body=body
            )
        if op == "bind_bulk":
            (namespace,) = args
            return self._do(
                "POST",
                f"/api/v1/namespaces/{namespace or 'default'}/bulkbindings",
                body=body,
            )
        if op == "create_events_bulk":
            (namespace,) = args
            return self._do(
                "POST",
                f"/api/v1/namespaces/{namespace or 'default'}/bulkevents",
                body=body,
            )
        if op in ("create_bulk", "update_bulk", "delete_bulk"):
            resource, namespace = args
            suffix = {
                "create_bulk": ":bulk",
                "update_bulk": ":bulkupdate",
                "delete_bulk": ":bulkdelete",
            }[op]
            payload = (
                {"names": body} if op == "delete_bulk" else {"items": body}
            )
            return self._do(
                "POST",
                self._collection_path(resource, namespace) + suffix,
                body=payload,
            )
        if op == "finalize_namespace":
            (name,) = args
            return self._do("PUT", f"/api/v1/namespaces/{name}/finalize", body=body)
        if op == "pod_log":
            namespace, name, container, tail = args
            return self._do(
                "GET",
                f"/api/v1/namespaces/{namespace or 'default'}/pods/{name}/log",
                query={
                    "container": container,
                    "tailLines": str(tail) if tail is not None else "",
                },
                raw=True,
            )
        if op == "pod_exec":
            namespace, name, container = args
            return self._do(
                "POST",
                f"/api/v1/namespaces/{namespace or 'default'}/pods/{name}/exec",
                query={"container": container},
                body=body,
            )
        raise ValueError(f"unknown op {op!r}")

    def watch(self, resource, namespace, since, lsel, fsel):
        info = RESOURCES[resource]
        if info.namespaced and namespace:
            path = f"/api/v1/watch/namespaces/{namespace}/{info.name}"
        else:
            path = f"/api/v1/watch/{info.name}"
        query = urlencode(
            {
                k: v
                for k, v in {
                    "resourceVersion": str(since) if since else "",
                    "labelSelector": lsel,
                    "fieldSelector": fsel,
                }.items()
                if v
            }
        )
        if query:
            path += "?" + query
        # Bound the dial + response-header phase (a wedged apiserver
        # must not hang the caller forever), then clear the socket
        # timeout once the stream is established: watch connections
        # are LONG-lived and legitimately silent for minutes, and a
        # read timeout mid-readline would tear down every idle watch.
        # A dial/handshake failure rotates through the remaining
        # endpoints once before propagating — the Reflector then
        # resumes the watch on the replica it landed on.
        last_exc = None
        for _ in range(max(1, len(self.endpoints))):
            try:
                conn = self._connect(timeout=self.timeout)
                conn.request("GET", path, headers=self.headers)
                resp = conn.getresponse()
                break
            except _STALE_ERRORS as e:
                last_exc = e
                self._rotate()
        else:
            raise last_exc
        if resp.status >= 400:
            data = json.loads(resp.read() or b"{}")
            conn.close()
            raise APIError(
                data.get("code", resp.status),
                data.get("reason", "Unknown"),
                data.get("message", f"HTTP {resp.status}"),
            )
        if conn.sock is not None:
            conn.sock.settimeout(None)
        return _HTTPWatchStream(conn, resp)


class Client:
    """Typed client over a Transport. Optional QPS throttle mirrors the
    reference's client-side rate limiting (RESTClient throttle,
    pkg/client/helper.go)."""

    def __init__(self, transport: Transport, qps: float = 0.0, burst: int = 10):
        self.t = transport
        self._bucket = TokenBucket(qps, burst) if qps > 0 else None
        self._recorder_lock = threading.Lock()
        self._broadcaster = None
        self._recorders: dict = {}

    def _throttle(self):
        if self._bucket is not None:
            self._bucket.accept()

    @staticmethod
    def _typed(resource: str, wire: dict):
        return serde.from_wire(RESOURCES[resource].cls, wire)

    @staticmethod
    def _wire(obj) -> dict:
        return obj if isinstance(obj, dict) else serde.to_wire(obj)

    # -- verbs --------------------------------------------------------

    def create(self, resource: str, obj, namespace: str = ""):
        self._throttle()
        out = self.t.request("POST", "create", (resource, namespace), self._wire(obj))
        return self._typed(resource, out)

    def get(self, resource: str, name: str, namespace: str = ""):
        self._throttle()
        out = self.t.request("GET", "get", (resource, namespace, name))
        return self._typed(resource, out)

    def list(
        self,
        resource: str,
        namespace: str = "",
        label_selector: str = "",
        field_selector: str = "",
    ) -> Tuple[List[Any], int]:
        self._throttle()
        out = self.t.request(
            "GET", "list", (resource, namespace, label_selector, field_selector)
        )
        version = int(out.get("metadata", {}).get("resourceVersion", "0") or "0")
        return [self._typed(resource, o) for o in out.get("items", [])], version

    def update(self, resource: str, obj, namespace: str = ""):
        wire = self._wire(obj)
        name = wire.get("metadata", {}).get("name", "")
        self._throttle()
        out = self.t.request("PUT", "update", (resource, namespace, name), wire)
        return self._typed(resource, out)

    def update_status(self, resource: str, obj, namespace: str = ""):
        wire = self._wire(obj)
        name = wire.get("metadata", {}).get("name", "")
        self._throttle()
        out = self.t.request(
            "PUT", "update_status", (resource, namespace, name), wire
        )
        return self._typed(resource, out)

    def delete(
        self,
        resource: str,
        name: str,
        namespace: str = "",
        grace_period_seconds: Optional[int] = None,
    ) -> None:
        """Delete; grace_period_seconds > 0 on a bound pod marks it
        Terminating instead of removing it (the kubelet confirms with a
        grace-0 delete at the stamped deadline). None/0 = immediate —
        the pre-graceful behavior every existing caller relies on."""
        self._throttle()
        args = (resource, namespace, name)
        if grace_period_seconds is not None:
            args = args + (grace_period_seconds,)
        self.t.request("DELETE", "delete", args)

    def evict(
        self,
        name: str,
        namespace: str = "default",
        grace_period_seconds: Optional[int] = None,
    ):
        """POST the pods/{name}/eviction subresource — graceful delete
        with an Eviction body (the preemption path's victim exit)."""
        self._throttle()
        opts = {}
        if grace_period_seconds is not None:
            opts["gracePeriodSeconds"] = int(grace_period_seconds)
        body = {
            "kind": "Eviction",
            "apiVersion": "v1",
            "metadata": {"name": name, "namespace": namespace},
            "deleteOptions": opts,
        }
        return self.t.request("POST", "evict_pod", (namespace, name), body)

    def patch(
        self,
        resource: str,
        name: str,
        patch,
        namespace: str = "",
        patch_type: str = "merge",
    ):
        """PATCH with any reference patch type (resthandler.go:446):
        "merge" (RFC 7386 dict), "json" (RFC 6902 op array),
        "strategic" (strategic merge — object lists merge by key)."""
        if patch_type not in ("merge", "json", "strategic"):
            raise ValueError(f"unknown patch type {patch_type!r}")
        self._throttle()
        out = self.t.request(
            "PATCH", "patch", (resource, namespace, name), patch,
            patch_type=patch_type,
        )
        return self._typed(resource, out)

    def pod_logs(
        self,
        name: str,
        namespace: str = "default",
        container: str = "",
        tail: Optional[int] = None,
    ) -> str:
        """GET /pods/{name}/log (relayed through the apiserver from the
        pod's kubelet)."""
        self._throttle()
        return self.t.request("GET", "pod_log", (namespace, name, container, tail))

    def pod_exec(
        self,
        name: str,
        command: List[str],
        namespace: str = "default",
        container: str = "",
    ) -> dict:
        """POST /pods/{name}/exec — returns {"exitCode", "output"}."""
        self._throttle()
        return self.t.request(
            "POST", "pod_exec", (namespace, name, container), {"command": command}
        )

    def finalize_namespace(self, name: str, finalizers) -> None:
        """PUT the namespace 'finalize' subresource
        (pkg/registry/namespace/etcd FinalizeREST)."""
        self._throttle()
        self.t.request(
            "PUT",
            "finalize_namespace",
            (name,),
            {"kind": "Namespace", "metadata": {"name": name},
             "spec": {"finalizers": list(finalizers)}},
        )

    def bind_bulk(
        self, bindings, namespace: str = "default", atomic: bool = False
    ) -> list:
        """Commit many (pod_name, node_name) bindings in one request;
        returns per-item Status dicts (the batch solver's commit path).
        atomic=True is the gang-commit mode: the first conflict rejects
        the whole batch server-side and no pod is bound."""
        wire = [
            {
                "kind": "Binding",
                "apiVersion": "v1",
                "metadata": {"name": p, "namespace": namespace},
                "target": {"kind": "Node", "name": n},
            }
            for p, n in bindings
        ]
        body = {"bindings": wire}
        if atomic:
            body["atomic"] = True
        self._throttle()
        out = self.t.request("POST", "bind_bulk", (namespace,), body)
        if isinstance(out, dict):
            return out.get("results", [])
        return out

    def create_bulk(self, resource: str, objs, namespace: str = "") -> list:
        """Create N objects in ONE request through the server's bulk
        fast path (one store lock hold, one WAL group commit). Returns
        per-item Status dicts in input order; a failed item never
        aborts the rest."""
        wire = [self._wire(o) for o in objs]
        self._throttle()
        out = self.t.request(
            "POST", "create_bulk", (resource, namespace), wire
        )
        if isinstance(out, dict):
            return out.get("results", [])
        return out

    def update_bulk(self, resource: str, objs, namespace: str = "") -> list:
        """Replace N objects in one request (CAS per item when the
        object carries metadata.resourceVersion)."""
        wire = [self._wire(o) for o in objs]
        self._throttle()
        out = self.t.request(
            "POST", "update_bulk", (resource, namespace), wire
        )
        if isinstance(out, dict):
            return out.get("results", [])
        return out

    def delete_bulk(self, resource: str, names, namespace: str = "") -> list:
        """Immediately delete N objects by name in one request."""
        self._throttle()
        out = self.t.request(
            "POST", "delete_bulk", (resource, namespace), list(names)
        )
        if isinstance(out, dict):
            return out.get("results", [])
        return out

    def create_events_bulk(self, events, namespace: str = "default") -> list:
        """Write many Events in one request (the broadcaster sink's
        batched path; each event's own metadata.namespace wins)."""
        self._throttle()
        out = self.t.request(
            "POST", "create_events_bulk", (namespace,), {"items": list(events)}
        )
        if isinstance(out, dict):
            return out.get("results", [])
        return out

    def bind(self, pod_name: str, node_name: str, namespace: str = "default") -> None:
        """POST a Binding (scheduler commit; factory.go:311-315)."""
        self._throttle()
        binding = {
            "kind": "Binding",
            "apiVersion": "v1",
            "metadata": {"name": pod_name, "namespace": namespace},
            "target": {"kind": "Node", "name": node_name},
        }
        self.t.request("POST", "bind", (namespace,), binding)

    def watch(
        self,
        resource: str,
        namespace: str = "",
        since: int = 0,
        label_selector: str = "",
        field_selector: str = "",
    ):
        """Raw watch stream of wire-form Events."""
        return self.t.watch(resource, namespace, since, label_selector, field_selector)

    # -- events (reference: pkg/client/record EventRecorder) ----------

    def record_event(
        self,
        involved,
        reason: str,
        message: str,
        source: str = "",
        namespace: str = "default",
    ) -> None:
        """Record through the shared broadcaster: async, deduped
        (repeats compress into one Event with a rising count —
        reference events_cache.go:52-69)."""
        wire = self._wire(involved)
        if not wire.get("metadata", {}).get("namespace"):
            wire = dict(wire, metadata=dict(wire.get("metadata", {}),
                                            namespace=namespace))
        self.recorder(source).event(wire, reason, message)

    def recorder(self, component: str = ""):
        """Component-scoped EventRecorder on this client's shared
        broadcaster+sink (lazily started)."""
        with self._recorder_lock:
            if self._broadcaster is None:
                from kubernetes_tpu.client.record import EventBroadcaster

                self._broadcaster = EventBroadcaster().start_recording_to_sink(self)
            rec = self._recorders.get(component)
            if rec is None:
                rec = self._recorders[component] = self._broadcaster.new_recorder(
                    component
                )
            return rec

    def flush_events(self, timeout: float = 2.0) -> None:
        """Block until previously recorded events have been written
        through the sink (tests / clean shutdown)."""
        with self._recorder_lock:
            b = self._broadcaster
        if b is not None:
            b.flush(timeout)
