"""Fault-injecting client transport.

Reference: pkg/client/chaosclient/chaosclient.go — a RoundTripper
wrapper that injects failures by policy so retry/backoff paths get
exercised under test instead of trusted on faith. This wraps any
Transport: each request consults the seeded policy and either fails
(APIError or raised ConnectionError), delays, or passes through.
"""

from __future__ import annotations

import random
import time
from typing import Optional

from kubernetes_tpu.client.rest import Transport
from kubernetes_tpu.server.api import APIError


class ChaosPolicy:
    """Seeded failure policy. Probabilities are per-request."""

    def __init__(
        self,
        seed: int = 0,
        p_error: float = 0.0,  # APIError 500 (server-side failure)
        p_network: float = 0.0,  # ConnectionError (transport failure)
        p_delay: float = 0.0,
        delay_s: float = 0.05,
        max_failures: Optional[int] = None,  # stop injecting after N
    ):
        self.rng = random.Random(seed)
        self.p_error = p_error
        self.p_network = p_network
        self.p_delay = p_delay
        self.delay_s = delay_s
        self.max_failures = max_failures
        self.failures = 0
        self.requests = 0

    def act(self) -> None:
        """Raise/delay per policy; returns normally to pass through."""
        self.requests += 1
        budget = (
            self.max_failures is None or self.failures < self.max_failures
        )
        roll = self.rng.random()
        fail_band = self.p_network + self.p_error
        if budget and roll < self.p_network:
            self.failures += 1
            raise ConnectionError("chaos: injected connection failure")
        if budget and roll < fail_band:
            self.failures += 1
            raise APIError(500, "InternalError", "chaos: injected server error")
        # Delay band is [fail_band, fail_band + p_delay): a roll in the
        # failure band with an exhausted budget passes through instead
        # of silently becoming a delay.
        if fail_band <= roll < fail_band + self.p_delay:
            time.sleep(self.delay_s)


class ChaosTransport(Transport):
    """Wraps a Transport; every request and watch-open passes through
    the policy first."""

    def __init__(self, inner: Transport, policy: ChaosPolicy):
        self.inner = inner
        self.policy = policy

    def request(self, verb, op, args, body=None):
        self.policy.act()
        return self.inner.request(verb, op, args, body)

    def watch(self, resource, namespace, since, lsel, fsel):
        self.policy.act()
        return self.inner.watch(resource, namespace, since, lsel, fsel)
