"""Event recording: broadcaster, recorder, dedup/compression.

Reference: pkg/client/record/event.go (EventBroadcaster +
EventRecorder.Eventf -> sinks) and events_cache.go:52-69 (aggregation:
events identical in (source, involvedObject, reason, message) within
the cache window become ONE Event whose count/lastTimestamp advance —
design doc docs/design/event_compression.md).

Events are observability, never control flow: recording is async and
every failure is swallowed (the reference drops events on sink errors
too, after retries).
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from kubernetes_tpu.models.objects import now_iso

# Process-wide event-name uniquifier (itertools.count is GIL-atomic).
_event_seq = itertools.count()

# Aggregation cache: the reference uses an LRU of 4096 with no TTL; a
# TTL keeps long-lived daemons from resurrecting week-old counts.
_CACHE_TTL = 3600.0
_CACHE_MAX = 4096


def _event_key(ev: dict) -> Tuple:
    inv = ev.get("involvedObject", {})
    return (
        ev.get("source", {}).get("component", ""),
        inv.get("kind", ""),
        inv.get("namespace", ""),
        inv.get("name", ""),
        inv.get("uid", ""),
        ev.get("reason", ""),
        ev.get("message", ""),
    )


@dataclass
class _CacheEntry:
    name: str  # stored event's object name
    namespace: str
    count: int
    first_timestamp: str
    last_seen: float = field(default_factory=time.monotonic)


class EventAggregator:
    """Dedup state (reference: events_cache.go eventsCache)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: Dict[Tuple, _CacheEntry] = {}

    def observe(self, ev: dict) -> Optional[_CacheEntry]:
        """Returns the existing entry (bumped) when `ev` is a repeat,
        else None (and starts tracking it once recorded)."""
        key = _event_key(ev)
        now = time.monotonic()
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None and now - entry.last_seen < _CACHE_TTL:
                entry.count += 1
                entry.last_seen = now
                return entry
            return None

    def track(self, ev: dict) -> None:
        key = _event_key(ev)
        with self._lock:
            if len(self._entries) >= _CACHE_MAX:
                # Evict oldest-seen (simple scan; 4096 max).
                oldest = min(self._entries, key=lambda k: self._entries[k].last_seen)
                del self._entries[oldest]
            self._entries[key] = _CacheEntry(
                name=ev["metadata"]["name"],
                namespace=ev["metadata"]["namespace"],
                count=int(ev.get("count", 1)),
                first_timestamp=ev.get("firstTimestamp", ""),
            )


class EventRecorder:
    """Component-scoped recorder (reference: EventRecorder.Eventf)."""

    def __init__(self, broadcaster: "EventBroadcaster", component: str):
        self.broadcaster = broadcaster
        self.component = component

    def event(self, involved, reason: str, message: str) -> None:
        wire = involved if isinstance(involved, dict) else None
        if wire is None:
            from kubernetes_tpu.models import serde

            wire = serde.to_wire(involved)
        meta = wire.get("metadata", {})
        ns = meta.get("namespace", "") or "default"
        ts = now_iso()
        self.broadcaster.emit(
            {
                "kind": "Event",
                "apiVersion": "v1",
                "metadata": {
                    # Timestamp + per-process monotonic counter: two
                    # distinct events in the same microsecond must not
                    # collide, or the second create 409s and the event
                    # is silently lost (advisor finding r1).
                    "name": (
                        f"{meta.get('name', 'unknown')}"
                        f".{int(time.time() * 1e6):x}.{next(_event_seq):x}"
                    ),
                    "namespace": ns,
                },
                "involvedObject": {
                    "kind": wire.get("kind", ""),
                    "name": meta.get("name", ""),
                    "namespace": ns,
                    "uid": meta.get("uid", ""),
                },
                "reason": reason,
                "message": message,
                "source": {"component": self.component},
                "firstTimestamp": ts,
                "lastTimestamp": ts,
                "count": 1,
            }
        )

    def eventf(self, involved, reason: str, message_fmt: str, *args) -> None:
        self.event(involved, reason, message_fmt % args if args else message_fmt)


class _SinkHandler:
    """API sink with dedup + batched writes. Callable per-event (the
    generic watcher shape) and batch-capable (`batch`), which the
    broadcaster's drain loop prefers."""

    def __init__(self, client):
        self.client = client
        self.aggregator = EventAggregator()
        self._bulk_ok: Optional[bool] = None  # None = probe on first batch

    def _bump_repeat(self, entry: _CacheEntry, ev: dict) -> None:
        """Repeat: advance count/lastTimestamp on the stored event."""
        try:
            stored = self.client.get(
                "events", entry.name, namespace=entry.namespace
            )
            stored.count = entry.count
            stored.last_timestamp = now_iso()
            self.client.update("events", stored, namespace=entry.namespace)
        except Exception:
            # The stored event expired from the TTL'd events resource:
            # re-create it (carrying the running count) instead of
            # going dark for the cache TTL.
            self._create_one(dict(ev, count=entry.count))

    def _create_one(self, ev: dict) -> None:
        try:
            self.client.create(
                "events", ev, namespace=ev["metadata"]["namespace"]
            )
            self.aggregator.track(ev)
        except Exception:
            pass

    def __call__(self, ev: dict) -> None:
        entry = self.aggregator.observe(ev)
        if entry is not None:
            self._bump_repeat(entry, ev)
            return
        self._create_one(ev)

    def batch(self, evs: List[dict]) -> None:
        fresh: List[dict] = []
        in_batch: Dict[Tuple, dict] = {}  # repeats WITHIN the burst
        for ev in evs:
            key = _event_key(ev)
            first = in_batch.get(key)
            if first is not None:
                # Compress into the burst's first occurrence — the
                # created event carries the accumulated count, exactly
                # like sequential dedup would have produced.
                first["count"] = int(first.get("count", 1)) + 1
                first["lastTimestamp"] = ev.get(
                    "lastTimestamp", first.get("lastTimestamp", "")
                )
                continue
            entry = self.aggregator.observe(ev)
            if entry is not None:
                self._bump_repeat(entry, ev)  # repeats are rare
            else:
                in_batch[key] = ev
                fresh.append(ev)
        if not fresh:
            return
        if len(fresh) == 1 or self._bulk_ok is False:
            for ev in fresh:
                self._create_one(ev)
            return
        # Capability probe happens at ATTRIBUTE RESOLUTION, not by
        # classifying exceptions from inside the call: a genuine
        # AttributeError raised WITHIN create_events_bulk (a bug in a
        # custom transport, or the in-process server path — the
        # LocalTransport executes the API handler on this thread) must
        # surface as a transient failure, not permanently disable the
        # bulk path (ADVICE r5).
        if not hasattr(self.client, "create_events_bulk"):
            self._bulk_ok = False
            for ev in fresh:
                self._create_one(ev)
            return
        try:
            results = self.client.create_events_bulk(fresh)
            self._bulk_ok = True
        except Exception as e:
            # Distinguish "this server/transport has no bulk path"
            # (probe result: fall back per-event, permanently) from a
            # transient transport failure AFTER the server may already
            # have applied the batch — re-creating there would write
            # duplicates, so DROP instead (events are observability;
            # the reference drops on sink errors too) and leave
            # _bulk_ok for the next burst to re-probe. ValueError is a
            # transport-level "unknown op" probe (Transport.request
            # raises it for ops it does not model); APIError
            # 400/404/405 is the server-side probe.
            from kubernetes_tpu.server.api import APIError

            unsupported = isinstance(e, (ValueError, TypeError)) or (
                isinstance(e, APIError) and e.code in (400, 404, 405)
            )
            if unsupported:
                self._bulk_ok = False
                for ev in fresh:
                    self._create_one(ev)
            return
        for ev, res in zip(fresh, results):
            if isinstance(res, dict) and res.get("status") == "Success":
                self.aggregator.track(ev)


class EventBroadcaster:
    """Fan-out hub: recorders push, sinks drain asynchronously
    (reference: event.go NewBroadcaster over watch.Mux)."""

    def __init__(self, queue_len: int = 1000):
        self._queue: "queue.Queue[Optional[dict]]" = queue.Queue(maxsize=queue_len)
        self._watchers: List[Callable[[dict], None]] = []
        self._threads: List[threading.Thread] = []
        self._lock = threading.Lock()
        self._started = False
        self._stopped = False

    def new_recorder(self, component: str = "") -> EventRecorder:
        return EventRecorder(self, component)

    def emit(self, ev: dict) -> None:
        try:
            self._queue.put_nowait(ev)
        except queue.Full:
            pass  # observability must never block or break callers

    def start_logging(self, log_fn: Callable[[str], None]) -> "EventBroadcaster":
        def handler(ev: dict) -> None:
            inv = ev.get("involvedObject", {})
            log_fn(
                f"event: {inv.get('namespace', '')}/{inv.get('name', '')} "
                f"{ev.get('reason', '')}: {ev.get('message', '')}"
            )

        return self._add_watcher(handler)

    def start_recording_to_sink(self, client) -> "EventBroadcaster":
        """Write events through the dedup cache to the events API
        (reference: StartRecordingToSink + recordToSink). Under load
        the sink batches: a drain burst of fresh events goes out as ONE
        bulk request (create_events_bulk) instead of one POST each —
        at 1k+ binds/s the per-event POSTs were the control plane's
        single largest per-pod cost. Falls back to per-event creates
        when the transport/server lacks the bulk path."""
        return self._add_watcher(_SinkHandler(client))

    def _add_watcher(self, handler: Callable[[dict], None]) -> "EventBroadcaster":
        with self._lock:
            self._watchers.append(handler)
            if not self._started:
                self._started = True
                t = threading.Thread(target=self._drain, daemon=True)
                t.start()
                self._threads.append(t)
        return self

    def flush(self, timeout: float = 2.0) -> bool:
        """Block until everything enqueued BEFORE this call has been
        fully handled by all sinks (marker ride-through)."""
        done = threading.Event()
        try:
            self._queue.put(("__flush__", done), timeout=timeout)
        except queue.Full:
            return False
        return done.wait(timeout)

    _BURST = 64  # max events delivered per batch

    def _deliver(self, burst: List[dict]) -> None:
        if not burst:
            return
        with self._lock:
            watchers = list(self._watchers)
        for w in watchers:
            batch = getattr(w, "batch", None)
            if batch is not None:
                try:
                    batch(burst)
                except Exception:
                    pass
            else:
                # Per-event guard: one raising callback must not drop
                # the rest of the burst for this watcher.
                for ev in burst:
                    try:
                        w(ev)
                    except Exception:
                        pass

    def _drain(self) -> None:
        while True:
            ev = self._queue.get()
            stopping = False
            burst: List[dict] = []
            while True:
                if ev is None:
                    stopping = True
                    break
                if isinstance(ev, tuple) and ev[0] == "__flush__":
                    # Everything enqueued before the marker is either
                    # already delivered or in `burst`: deliver, then ack.
                    self._deliver(burst)
                    burst = []
                    ev[1].set()
                else:
                    burst.append(ev)
                    if len(burst) >= self._BURST:
                        break
                try:
                    ev = self._queue.get_nowait()
                except queue.Empty:
                    break
            self._deliver(burst)
            if stopping:
                return

    def shutdown(self, timeout: float = 2.0) -> None:
        """Flush then stop the drain thread."""
        with self._lock:
            if self._stopped:
                return
            self._stopped = True
        self._queue.put(None)
        for t in self._threads:
            t.join(timeout=timeout)
