"""kubeconfig file loading.

Reference: pkg/client/clientcmd/ — clusters/users/contexts files with a
current-context pointer, merged with command-line overrides. This loads
the same schema (YAML or JSON) and resolves the pieces ktctl needs:
server URL and auth credentials.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Dict, Optional

DEFAULT_PATHS = (
    os.path.expanduser("~/.ktconfig"),
    os.path.expanduser("~/.kube/config"),
)


class KubeconfigError(Exception):
    pass


@dataclass
class ClientConfig:
    """Resolved connection settings for one context."""

    server: str = "http://127.0.0.1:8080"
    username: str = ""
    password: str = ""
    token: str = ""
    context: str = ""
    namespace: str = ""

    def auth_headers(self) -> Dict[str, str]:
        if self.token:
            return {"Authorization": f"Bearer {self.token}"}
        if self.username:
            import base64

            cred = base64.b64encode(
                f"{self.username}:{self.password}".encode()
            ).decode()
            return {"Authorization": f"Basic {cred}"}
        return {}


def _parse(text: str) -> dict:
    try:
        data = json.loads(text)
    except json.JSONDecodeError:
        import yaml

        try:
            data = yaml.safe_load(text)
        except yaml.YAMLError as e:
            raise KubeconfigError(f"malformed kubeconfig: {e}")
    if not isinstance(data, dict):
        raise KubeconfigError("kubeconfig is not a mapping")
    return data


def _by_name(entries, name: str) -> Optional[dict]:
    for e in entries or []:
        if e.get("name") == name:
            return e
    return None


def config_path(path: Optional[str] = None) -> str:
    """The file `ktctl config` subcommands read and write: explicit
    path, $KTCONFIG/$KUBECONFIG, an existing default, else the first
    default location (created on first write) — mirroring clientcmd's
    ModifyConfig destination rules."""
    if path:
        return path
    for var in ("KTCONFIG", "KUBECONFIG"):
        if os.environ.get(var):
            return os.environ[var]
    for p in DEFAULT_PATHS:
        if os.path.exists(p):
            return p
    return DEFAULT_PATHS[0]


def load_raw(path: str) -> dict:
    """The kubeconfig file as a plain dict (empty skeleton when the
    file doesn't exist yet)."""
    if not os.path.exists(path):
        return {
            "apiVersion": "v1",
            "kind": "Config",
            "clusters": [],
            "users": [],
            "contexts": [],
            "current-context": "",
        }
    with open(path) as f:
        data = _parse(f.read())
    for section in ("clusters", "users", "contexts"):
        data.setdefault(section, [])
    return data


def save_raw(path: str, data: dict) -> None:
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")


def set_entry(data: dict, section: str, name: str, body_key: str, body: dict) -> None:
    """Create-or-merge a named clusters/users/contexts entry (clientcmd
    set-cluster/set-credentials/set-context semantics: existing keys
    not mentioned are kept)."""
    entry = _by_name(data.get(section), name)
    if entry is None:
        entry = {"name": name, body_key: {}}
        data.setdefault(section, []).append(entry)
    entry.setdefault(body_key, {}).update(body)


def load_kubeconfig(
    path: Optional[str] = None, context: Optional[str] = None
) -> ClientConfig:
    """Load and resolve a kubeconfig. Search order mirrors the
    reference loader: explicit path, $KTCONFIG / $KUBECONFIG, then the
    default home locations; a missing file yields defaults (local
    cluster), a malformed one raises."""
    if path:
        # An EXPLICIT path must exist — falling back to the operator's
        # personal config would silently point writes elsewhere.
        if not os.path.exists(path):
            raise KubeconfigError(f"kubeconfig {path!r} not found")
        chosen = path
    else:
        candidates = [
            os.environ[var]
            for var in ("KTCONFIG", "KUBECONFIG")
            if os.environ.get(var)
        ]
        candidates.extend(DEFAULT_PATHS)
        chosen = next((c for c in candidates if os.path.exists(c)), None)
        if chosen is None:
            return ClientConfig()
    with open(chosen) as f:
        data = _parse(f.read())

    ctx_name = context or data.get("current-context", "")
    ctx = _by_name(data.get("contexts"), ctx_name)
    if ctx is None and ctx_name:
        # A NAMED context that doesn't exist is an error (clientcmd
        # validation) — silently defaulting to localhost would point
        # writes at the wrong cluster.
        raise KubeconfigError(f"context {ctx_name!r} not found in {chosen}")
    ctx = ctx or {}
    ctx_body = ctx.get("context", {})
    cluster = _by_name(data.get("clusters"), ctx_body.get("cluster", "")) or {}
    user = _by_name(data.get("users"), ctx_body.get("user", "")) or {}
    cluster_body = cluster.get("cluster", {})
    user_body = user.get("user", {})
    return ClientConfig(
        server=cluster_body.get("server", "http://127.0.0.1:8080"),
        username=user_body.get("username", ""),
        password=user_body.get("password", ""),
        token=user_body.get("token", ""),
        context=ctx_name,
        namespace=ctx_body.get("namespace", ""),
    )
