"""List/watch cache substrate: ThreadSafeStore/Indexer, FIFO/DeltaFIFO,
ExpirationCache, UndeltaStore, Reflector, Informer.

Reference: pkg/client/cache/ (store.go, index.go, fifo.go,
delta_fifo.go, expiration_cache.go, undelta_store.go,
reflector.go:80-268) and pkg/controller/framework/controller.go
(NewInformer). The Reflector lists, primes its store, then applies
watch deltas; on watch failure it backs off and re-lists — components
therefore tolerate apiserver restarts and compaction (410 Gone)
transparently.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

from kubernetes_tpu.server.api import APIError
from kubernetes_tpu.store.watch import ADDED, DELETED, ERROR, MODIFIED
from kubernetes_tpu.utils import sanitizer


def meta_namespace_key(obj) -> str:
    """Default key func (reference: cache.MetaNamespaceKeyFunc)."""
    if isinstance(obj, dict):
        meta = obj.get("metadata", {})
        ns, name = meta.get("namespace", ""), meta.get("name", "")
    else:
        ns, name = obj.metadata.namespace, obj.metadata.name
    return f"{ns}/{name}" if ns else name


class ThreadSafeStore:
    """Keyed object cache (reference: cache.ThreadSafeStore)."""

    def __init__(self, key_func: Callable = meta_namespace_key):
        self._lock = sanitizer.rlock("informer.store")
        self._items: Dict[str, Any] = {}
        self.key_func = key_func

    def add(self, obj) -> None:
        with self._lock:
            self._items[self.key_func(obj)] = obj

    def update(self, obj) -> None:
        # A real method, not `update = add`: class-time binding would
        # freeze THIS add, bypassing subclass overrides (Indexer would
        # never re-index on MODIFIED events, ExpirationCache never
        # refresh, UndeltaStore never push).
        self.add(obj)

    def delete(self, obj) -> None:
        with self._lock:
            self._items.pop(self.key_func(obj), None)

    def get(self, key: str):
        with self._lock:
            return self._items.get(key)

    def list(self) -> List[Any]:
        with self._lock:
            return list(self._items.values())

    def keys(self) -> List[str]:
        with self._lock:
            return list(self._items.keys())

    def replace(self, objs: List[Any]) -> None:
        with self._lock:
            self._items = {self.key_func(o): o for o in objs}

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)


class Indexer(ThreadSafeStore):
    """ThreadSafeStore with named secondary indexes (reference:
    cache.Indexer, index.go). An index func maps an object to a list
    of index values; by_index(name, value) returns every object whose
    func emitted that value — e.g. pods by node, endpoints by service."""

    def __init__(
        self,
        indexers: Optional[Dict[str, Callable[[Any], List[str]]]] = None,
        key_func: Callable = meta_namespace_key,
    ):
        super().__init__(key_func)
        self.indexers = dict(indexers or {})
        # index name -> value -> set of object keys
        self._indices: Dict[str, Dict[str, set]] = {
            name: {} for name in self.indexers
        }
        # Reverse map: key -> [(index name, value), ...] it was indexed
        # under, so unindexing is O(entries for that key) instead of a
        # scan over every bucket of every index (which would serialize
        # readers behind thousands of set.discards per pod update).
        self._indexed_under: Dict[str, List[tuple]] = {}

    def _unindex(self, key: str) -> None:
        for name, value in self._indexed_under.pop(key, ()):
            bucket = self._indices[name].get(value)
            if bucket is not None:
                bucket.discard(key)
                if not bucket:
                    del self._indices[name][value]

    def _index(self, key: str, obj: Any) -> None:
        under = []
        for name, fn in self.indexers.items():
            for value in fn(obj):
                self._indices[name].setdefault(value, set()).add(key)
                under.append((name, value))
        if under:
            self._indexed_under[key] = under

    def add(self, obj) -> None:
        with self._lock:
            key = self.key_func(obj)
            self._unindex(key)
            self._items[key] = obj
            self._index(key, obj)

    def delete(self, obj) -> None:
        with self._lock:
            key = self.key_func(obj)
            self._unindex(key)
            self._items.pop(key, None)

    def replace(self, objs: List[Any]) -> None:
        with self._lock:
            self._items = {self.key_func(o): o for o in objs}
            self._indices = {name: {} for name in self.indexers}
            self._indexed_under = {}
            for key, obj in self._items.items():
                self._index(key, obj)

    def by_index(self, name: str, value: str) -> List[Any]:
        with self._lock:
            keys = self._indices.get(name, {}).get(value, ())
            return [self._items[k] for k in keys if k in self._items]

    def index_values(self, name: str) -> List[str]:
        with self._lock:
            return sorted(
                v for v, keys in self._indices.get(name, {}).items() if keys
            )


class ExpirationCache(ThreadSafeStore):
    """TTL store: entries vanish ttl seconds after their last add
    (reference: cache.ExpirationCache, expiration_cache.go — backs the
    scheduler's assumed-pods window)."""

    def __init__(self, ttl: float, key_func: Callable = meta_namespace_key):
        super().__init__(key_func)
        self.ttl = ttl
        self._stamps: Dict[str, float] = {}

    def _expire_locked(self) -> None:
        now = time.monotonic()
        for key in [k for k, t in self._stamps.items() if now - t > self.ttl]:
            del self._stamps[key]
            self._items.pop(key, None)

    def add(self, obj) -> None:
        with self._lock:
            self._expire_locked()
            key = self.key_func(obj)
            self._items[key] = obj
            self._stamps[key] = time.monotonic()

    def delete(self, obj) -> None:
        with self._lock:
            key = self.key_func(obj)
            self._items.pop(key, None)
            self._stamps.pop(key, None)

    def get(self, key: str):
        with self._lock:
            self._expire_locked()
            return self._items.get(key)

    def list(self) -> List[Any]:
        with self._lock:
            self._expire_locked()
            return list(self._items.values())

    def keys(self) -> List[str]:
        with self._lock:
            self._expire_locked()
            return list(self._items.keys())

    def __len__(self) -> int:
        with self._lock:
            self._expire_locked()
            return len(self._items)


class UndeltaStore(ThreadSafeStore):
    """Store that pushes the FULL current state to a callback on every
    change (reference: cache.UndeltaStore, undelta_store.go — feeds
    consumers that want snapshots, e.g. the proxy's OnUpdate).

    The snapshot is captured AND delivered under the store lock
    (reentrant), so pushes arrive in mutation order and the last push
    always reflects the final state; the callback must not block or
    mutate the store."""

    def __init__(
        self,
        push: Callable[[List[Any]], None],
        key_func: Callable = meta_namespace_key,
    ):
        super().__init__(key_func)
        self.push = push

    def add(self, obj) -> None:
        with self._lock:
            super().add(obj)
            self.push(self.list())

    def delete(self, obj) -> None:
        with self._lock:
            super().delete(obj)
            self.push(self.list())

    def replace(self, objs: List[Any]) -> None:
        with self._lock:
            super().replace(objs)
            self.push(self.list())


class FIFO:
    """Producer/consumer queue with key-dedup: a Pop returns the latest
    version of each enqueued object (reference: cache.FIFO, fifo.go:49-184)."""

    def __init__(self, key_func: Callable = meta_namespace_key):
        self._lock = sanitizer.lock("informer.fifo")
        self._cond = threading.Condition(self._lock)
        self._items: Dict[str, Any] = {}
        self._queue: List[str] = []
        self._closed = False
        self._wakes: List = []
        self.key_func = key_func

    def attach_wake(self, event) -> None:
        """Register a threading.Event set whenever the queue gains
        items (or closes). Event-driven consumers (the incremental
        scheduler's micro-ticks) wait on ONE event fed by several
        sources — queue arrivals, watch deltas, commit releases —
        instead of blocking inside pop() where only arrivals can wake
        them. Event.set is async-signal-cheap; no ordering is implied
        beyond 'something changed, sweep the queue'."""
        with self._cond:
            self._wakes.append(event)

    def _signal_locked(self) -> None:
        for ev in self._wakes:
            ev.set()

    def add(self, obj) -> None:
        key = self.key_func(obj)
        with self._cond:
            if key not in self._items:
                self._queue.append(key)
            self._items[key] = obj
            self._cond.notify()
            self._signal_locked()

    update = add

    def delete(self, obj) -> None:
        key = self.key_func(obj)
        with self._cond:
            self._items.pop(key, None)
            # Lazy removal: Pop skips keys without items.

    def pop(self, timeout: Optional[float] = None):
        """Blocking pop (reference: fifo.go:168). None on close/timeout."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while True:
                while self._queue:
                    key = self._queue.pop(0)
                    if key in self._items:
                        return self._items.pop(key)
                if self._closed:
                    return None
                wait = None
                if deadline is not None:
                    wait = deadline - time.monotonic()
                    if wait <= 0:
                        return None
                self._cond.wait(timeout=wait)

    def replace(self, objs: List[Any]) -> None:
        with self._cond:
            self._items = {self.key_func(o): o for o in objs}
            self._queue = list(self._items.keys())
            self._cond.notify_all()
            self._signal_locked()

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
            self._signal_locked()

    def __len__(self) -> int:
        with self._lock:
            return len([k for k in self._queue if k in self._items])


class DeltaFIFO:
    """FIFO of per-key DELTA LISTS (reference: cache.DeltaFIFO,
    delta_fifo.go). Unlike FIFO — whose key dedup silently drops
    deletions that race a pending add — a pop returns the ordered
    [(type, object), ...] history for one key since its last pop, so
    consumers observe every transition including Deleted. replace()
    emits Sync deltas and synthesizes Deleted for keys that vanished."""

    SYNC = "SYNC"

    def __init__(self, key_func: Callable = meta_namespace_key):
        self.key_func = key_func
        self._cond = threading.Condition(sanitizer.lock("informer.deltafifo"))
        self._deltas: Dict[str, List[tuple]] = {}
        self._queue: List[str] = []
        self._known: Dict[str, Any] = {}  # last object seen per key
        self._closed = False

    def _append(self, key: str, etype: str, obj: Any) -> None:
        if key not in self._deltas:
            self._deltas[key] = []
            self._queue.append(key)
        self._deltas[key].append((etype, obj))
        self._cond.notify()

    def add(self, obj) -> None:
        with self._cond:
            key = self.key_func(obj)
            etype = MODIFIED if key in self._known else ADDED
            self._known[key] = obj
            self._append(key, etype, obj)

    def update(self, obj) -> None:
        self.add(obj)

    def delete(self, obj) -> None:
        with self._cond:
            key = self.key_func(obj)
            self._known.pop(key, None)
            self._append(key, DELETED, obj)

    def replace(self, objs: List[Any]) -> None:
        with self._cond:
            new = {self.key_func(o): o for o in objs}
            for key, old in list(self._known.items()):
                if key not in new:
                    self._known.pop(key)
                    self._append(key, DELETED, old)
            for key, obj in new.items():
                self._known[key] = obj
                self._append(key, self.SYNC, obj)

    def pop(self, timeout: Optional[float] = None) -> Optional[List[tuple]]:
        """Oldest key's delta list, or None on close/timeout."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while True:
                if self._queue:
                    key = self._queue.pop(0)
                    return self._deltas.pop(key)
                if self._closed:
                    return None
                wait = None
                if deadline is not None:
                    wait = deadline - time.monotonic()
                    if wait <= 0:
                        return None
                self._cond.wait(timeout=wait)

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def __len__(self) -> int:
        with self._cond:
            return len(self._queue)


class Reflector:
    """List+watch loop feeding a store (reference: reflector.go:80-268).

    `store` needs add/update/delete/replace. Objects land in wire form
    unless `decode` converts them.
    """

    def __init__(
        self,
        client,
        resource: str,
        store,
        namespace: str = "",
        label_selector: str = "",
        field_selector: str = "",
        decode: Optional[Callable[[dict], Any]] = None,
        resync_period: float = 0.0,
        on_event: Optional[Callable] = None,
        decode_deleted: bool = True,
    ):
        self.client = client
        self.resource = resource
        self.store = store
        self.namespace = namespace
        self.label_selector = label_selector
        self.field_selector = field_selector
        self.decode = decode or (lambda o: o)
        self.resync_period = resync_period
        self.on_event = on_event
        # decode_deleted=False skips the typed decode for DELETED
        # events and hands the raw wire dict to store.delete/on_event:
        # deletions only need the KEY (meta_namespace_key reads dicts),
        # and on high-churn streams the discarded full decode is the
        # reflector thread's main cost. Opt-in — handlers must accept
        # wire dicts for deletes.
        self.decode_deleted = decode_deleted
        self.last_sync_version = 0
        # Monotonic time this reflector last processed a delta or
        # relist — the scheduler daemons' informer-staleness SLI
        # (utils/sli.INFORMER_STALENESS) reads it per solve tick.
        self.last_event_mono = 0.0
        # Watch-resume flag: set once a cycle reaches its watch phase,
        # cleared at each cycle start. When a cycle dies IN the watch
        # (endpoint rotated away, connection reset), the next cycle
        # skips the full re-LIST and resumes the watch from
        # last_sync_version — the new apiserver's watch cache usually
        # still covers it; 410 (compacted/too-old) falls back to LIST.
        self._resume_watch = False
        # Full LISTs issued (the resume regression test's observable).
        self.list_count = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._synced = threading.Event()
        self._stream = None  # in-flight watch; closed by stop()

    # -- lifecycle ----------------------------------------------------

    def start(self) -> "Reflector":
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        # Wake the consumer NOW: close() pushes a sentinel, so _consume
        # can block on long waits instead of polling (at 1000 kubelets
        # x 2 informers, a 0.2 s poll interval was 10k thread wakeups/s
        # of pure GIL thrash — the 1000-node drill's biggest cost).
        stream = self._stream
        if stream is not None:
            try:
                stream.close()
            except Exception:
                pass
        if self._thread:
            self._thread.join(timeout=5)

    def wait_for_sync(self, timeout: float = 10.0) -> bool:
        return self._synced.wait(timeout)

    # -- the loop -----------------------------------------------------

    def _run(self) -> None:
        backoff = 0.05
        while not self._stop.is_set():
            try:
                progressed = self._list_and_watch()
            except Exception:
                if self._stop.is_set():
                    return
                time.sleep(backoff)
                backoff = min(backoff * 2, 5.0)
                continue
            if progressed:
                backoff = 0.05
            elif not self._stop.is_set():
                # Idle-close fallback (watcher being shed): the re-list
                # itself must back off too, or a sustained drop storm
                # becomes a full-LIST tight loop against the very
                # server that is shedding us.
                self._stop.wait(backoff)
                backoff = min(backoff * 2, 5.0)

    def _list_and_watch(self) -> bool:
        """One LIST + watch cycle. Returns False only when the watch
        was abandoned after consecutive EMPTY closes (no event ever
        delivered) — _run then backs off before the next re-list."""
        resume = self._resume_watch and self.last_sync_version > 0
        self._resume_watch = False
        if not resume:
            self._list()

        # Consecutive watch closes that delivered NOTHING: the server
        # (or the store's slow-consumer guard, or an injected fault
        # storm) is shedding this watcher. Re-dialing instantly would
        # tight-loop list/watch against a struggling control plane —
        # back off between re-dials and, past the threshold, fall back
        # to a full re-list (return; _run owns that cadence).
        idle_closes = 0
        # From here on a transport failure (the apiserver died, the
        # client rotated endpoints) resumes the WATCH next cycle
        # instead of re-LISTing: the store is synced and
        # last_sync_version tracks every delivered event, so the new
        # replica's watch cache can usually serve the delta directly.
        self._resume_watch = True
        while not self._stop.is_set():
            try:
                stream = self.client.watch(
                    self.resource,
                    namespace=self.namespace,
                    since=self.last_sync_version,
                    label_selector=self.label_selector,
                    field_selector=self.field_selector,
                )
            except APIError as e:
                if e.code == 410:  # compacted/too-old: full re-list
                    self._resume_watch = False
                    return True
                raise
            self._stream = stream
            try:
                delivered = self._consume(stream)
            finally:
                self._stream = None
                stream.close()
            if self._stop.is_set():
                return True
            if delivered:
                idle_closes = 0
                continue
            idle_closes += 1
            if idle_closes >= self._RELIST_AFTER_IDLE_CLOSES:
                # Deliberate fallback: the watch window may be
                # unservable — the next cycle must LIST, not resume.
                self._resume_watch = False
                return False
            self._stop.wait(min(0.05 * (2 ** idle_closes), 2.0))
        return True

    def _list(self) -> None:
        """Full LIST + store replace + synthesized deltas (one half of
        a list/watch cycle; resumed cycles skip it)."""
        # Typed clients return (items, version); raw ones a wire dict.
        items, version = self.client.list(
            self.resource,
            namespace=self.namespace,
            label_selector=self.label_selector,
            field_selector=self.field_selector,
        )
        self.list_count += 1
        objs = [self.decode(o) if isinstance(o, dict) else o for o in items]
        # Objects that vanished during a watch outage must surface as
        # DELETED on relist (DeltaFIFO.replace synthesizes Deleted the
        # same way) — delta subscribers like the incremental scheduler
        # would otherwise carry phantom state forever.
        vanished = []
        if self.on_event is not None and hasattr(self.store, "keys"):
            key_func = getattr(self.store, "key_func", meta_namespace_key)
            new_keys = {key_func(o) for o in objs}
            for k in self.store.keys():
                if k not in new_keys:
                    old = self.store.get(k)
                    if old is not None:
                        vanished.append(old)
        self.store.replace(objs)
        self.last_sync_version = version
        self.last_event_mono = time.monotonic()
        self._synced.set()
        if self.on_event:
            for o in vanished:
                self.on_event(DELETED, o)
            for o in objs:
                self.on_event(ADDED, o)

    #: Empty watch closes tolerated before falling back to a re-list.
    _RELIST_AFTER_IDLE_CLOSES = 3

    def _consume(self, stream) -> int:
        """Drain `stream` until it closes; returns events processed
        (the close-backoff signal above)."""
        delivered = 0
        while not self._stop.is_set():
            # Long block: close() (from stop() or the store dropping a
            # slow consumer) wakes it immediately via the sentinel; the
            # timeout is only a safety net for the stop-vs-register
            # race.
            ev = stream.next(timeout=10.0)
            if ev is None:
                if stream.closed:
                    return delivered  # dropped; outer loop re-establishes
                continue
            if ev.type == ERROR:
                return delivered
            if (
                ev.type == DELETED
                and not self.decode_deleted
                and isinstance(ev.object, dict)
            ):
                obj = ev.object
            elif isinstance(ev.object, dict):
                obj = self.decode(ev.object)
            else:
                obj = ev.object
            if ev.version:
                self.last_sync_version = ev.version
            self.last_event_mono = time.monotonic()
            if ev.type == ADDED:
                self.store.add(obj)
            elif ev.type == MODIFIED:
                self.store.update(obj)
            elif ev.type == DELETED:
                self.store.delete(obj)
            delivered += 1
            if self.on_event:
                self.on_event(ev.type, obj)
        return delivered


class Informer:
    """Reflector + cache + event handlers (reference:
    framework.NewInformer, controller.go:201)."""

    def __init__(
        self,
        client,
        resource: str,
        namespace: str = "",
        label_selector: str = "",
        field_selector: str = "",
        decode: Optional[Callable] = None,
        on_add: Optional[Callable] = None,
        on_update: Optional[Callable] = None,
        on_delete: Optional[Callable] = None,
        decode_deleted: bool = True,
    ):
        self.store = ThreadSafeStore()
        self._on_add = on_add
        self._on_update = on_update
        self._on_delete = on_delete
        self.reflector = Reflector(
            client,
            resource,
            self.store,
            namespace=namespace,
            label_selector=label_selector,
            field_selector=field_selector,
            decode=decode,
            on_event=self._handle,
            decode_deleted=decode_deleted,
        )

    def _handle(self, etype: str, obj) -> None:
        if etype == ADDED and self._on_add:
            self._on_add(obj)
        elif etype == MODIFIED and self._on_update:
            self._on_update(obj)
        elif etype == DELETED and self._on_delete:
            self._on_delete(obj)

    def start(self) -> "Informer":
        self.reflector.start()
        return self

    def stop(self) -> None:
        self.reflector.stop()

    def wait_for_sync(self, timeout: float = 10.0) -> bool:
        return self.reflector.wait_for_sync(timeout)
