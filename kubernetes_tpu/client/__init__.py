"""Client library: typed REST client + list/watch cache substrate.

Reference: pkg/client/ (typed client, request.go), pkg/client/cache/
(Store, FIFO, Reflector, listers), pkg/controller/framework (Informer),
pkg/client/record (events).
"""

from kubernetes_tpu.client.rest import Client, HTTPTransport, LocalTransport
from kubernetes_tpu.client.cache import FIFO, Informer, Reflector, ThreadSafeStore

__all__ = [
    "Client",
    "HTTPTransport",
    "LocalTransport",
    "FIFO",
    "Informer",
    "Reflector",
    "ThreadSafeStore",
]
