"""Service dataplane (kube-proxy equivalent).

Reference: pkg/proxy/ — userspace TCP/UDP proxy with iptables portal
redirection and a round-robin load balancer with session affinity.

TPU-native framing: the portal layer is a pure rule table (the
iptables analog is data, not kernel state) so the whole service
routing function — clusterIP:port -> backend — is a deterministic
lookup that tests and the batch path can evaluate without root.
Actual packet shuffling remains a host-side userspace copy loop,
exactly as in the reference (proxysocket.go).
"""

from kubernetes_tpu.proxy.roundrobin import LoadBalancerRR
from kubernetes_tpu.proxy.ruletable import PortalRuleTable
from kubernetes_tpu.proxy.proxier import Proxier
from kubernetes_tpu.proxy.config import ServiceConfig, EndpointsConfig, ProxyServer

__all__ = [
    "LoadBalancerRR",
    "PortalRuleTable",
    "Proxier",
    "ServiceConfig",
    "EndpointsConfig",
    "ProxyServer",
]
