"""Service/endpoints config watchers + kube-proxy daemon assembly.

Reference: pkg/proxy/config/config.go:60-94 (ServiceConfig /
EndpointsConfig deliver full desired-state snapshots to handlers) and
cmd/kube-proxy/app/server.go:91-132 (wiring: config sources -> Proxier
+ LoadBalancerRR).
"""

from __future__ import annotations

import threading
from typing import Callable, List, Optional

from kubernetes_tpu.client.cache import Informer
from kubernetes_tpu.models import serde
from kubernetes_tpu.models.objects import Endpoints, Service
from kubernetes_tpu.proxy.proxier import Proxier
from kubernetes_tpu.proxy.roundrobin import LoadBalancerRR
from kubernetes_tpu.proxy.ruletable import PortalRuleTable


class _SnapshotConfig:
    """Watches one resource and delivers the FULL object list to each
    handler on every change (the reference's OnUpdate contract)."""

    def __init__(self, client, resource: str, decode: Callable):
        self._handlers: List[Callable] = []
        self._lock = threading.Lock()
        self.informer = Informer(
            client,
            resource,
            decode=decode,
            on_add=self._changed,
            on_update=self._changed,
            on_delete=self._changed,
        )

    def register_handler(self, handler: Callable) -> None:
        with self._lock:
            self._handlers.append(handler)

    def _changed(self, _obj) -> None:
        snapshot = self.informer.store.list()
        with self._lock:
            handlers = list(self._handlers)
        for h in handlers:
            try:
                h(snapshot)
            except Exception:
                pass

    def start(self):
        self.informer.start()
        return self

    def wait_for_sync(self, timeout: float = 10.0) -> bool:
        return self.informer.wait_for_sync(timeout)

    def stop(self) -> None:
        self.informer.stop()


class ServiceConfig(_SnapshotConfig):
    def __init__(self, client):
        super().__init__(
            client, "services", lambda w: serde.from_wire(Service, w)
        )


class EndpointsConfig(_SnapshotConfig):
    def __init__(self, client):
        super().__init__(
            client, "endpoints", lambda w: serde.from_wire(Endpoints, w)
        )


class ProxyServer:
    """The kube-proxy daemon: one Proxier + one LoadBalancerRR fed by
    service/endpoints watches (reference: cmd/kube-proxy/app/
    server.go:91-132)."""

    def __init__(
        self,
        client,
        listen_ip: str = "127.0.0.1",
        real_portals: bool = False,
    ):
        self.client = client
        self.lb = LoadBalancerRR()
        self.rules = PortalRuleTable()
        self.proxier = Proxier(
            self.lb, self.rules, listen_ip=listen_ip, real_portals=real_portals
        )
        self.service_config = ServiceConfig(client)
        self.endpoints_config = EndpointsConfig(client)
        self.service_config.register_handler(self.proxier.on_update)
        self.endpoints_config.register_handler(self.lb.on_update)

    def start(self) -> "ProxyServer":
        self.service_config.start()
        self.endpoints_config.start()
        self.service_config.wait_for_sync()
        self.endpoints_config.wait_for_sync()
        # Prime with current state — informer events may have fired
        # before handlers could see a complete snapshot.
        self.proxier.on_update(self.service_config.informer.store.list())
        self.lb.on_update(self.endpoints_config.informer.store.list())
        return self

    def stop(self) -> None:
        self.service_config.stop()
        self.endpoints_config.stop()
        self.proxier.stop()

    def resolve_portal(self, ip: str, port: int, protocol: str = "TCP"):
        """Where a client hitting clusterIP:port actually lands."""
        return self.rules.resolve(ip, port, protocol)
