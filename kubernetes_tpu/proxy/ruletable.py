"""Portal rule table — the iptables analog as pure data.

Reference: pkg/util/iptables/ (EnsureRule/DeleteRule around exec'd
iptables) + Proxier.openPortal/closePortal (pkg/proxy/proxier.go:376+)
which install DNAT redirects clusterIP:port -> proxier socket.

Here the "kernel" is an in-memory, thread-safe rule table: ensure_rule
and delete_rule carry the same idempotency contract as the reference's
wrapper (ensure reports whether the rule already existed), and
`resolve` performs the DNAT hop a real kernel would, so tests and the
in-process dataplane route exactly like the deployed system would.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

# (portal_ip, portal_port, protocol) -> redirect target
PortalKey = Tuple[str, int, str]


@dataclass(frozen=True)
class PortalRule:
    portal_ip: str
    portal_port: int
    protocol: str  # TCP | UDP
    proxy_ip: str
    proxy_port: int
    service: str = ""  # "ns/name:port" for observability


class PortalRuleTable:
    """DNAT-style portal redirection rules."""

    def __init__(self):
        self._lock = threading.Lock()
        self._rules: Dict[PortalKey, PortalRule] = {}

    @staticmethod
    def _key(ip: str, port: int, protocol: str) -> PortalKey:
        return (ip, port, protocol.upper())

    def ensure_rule(self, rule: PortalRule) -> bool:
        """Install a portal rule; True if it already existed (the
        reference's EnsureRule contract)."""
        key = self._key(rule.portal_ip, rule.portal_port, rule.protocol)
        with self._lock:
            existed = self._rules.get(key) == rule
            self._rules[key] = rule
            return existed

    def delete_rule(self, ip: str, port: int, protocol: str) -> None:
        with self._lock:
            self._rules.pop(self._key(ip, port, protocol), None)

    def resolve(
        self, ip: str, port: int, protocol: str = "TCP"
    ) -> Optional[Tuple[str, int]]:
        """The DNAT hop: where does traffic to this portal land?"""
        with self._lock:
            rule = self._rules.get(self._key(ip, port, protocol))
            return (rule.proxy_ip, rule.proxy_port) if rule else None

    def rules(self) -> List[PortalRule]:
        with self._lock:
            return list(self._rules.values())

    def flush(self) -> None:
        with self._lock:
            self._rules.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._rules)
