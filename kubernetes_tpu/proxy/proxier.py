"""Userspace service proxier.

Reference: pkg/proxy/proxier.go — Proxier.OnUpdate (:264-321) diffs the
desired service list against running portals, opens a listener socket
per service port (addServiceOnPort :222), installs portal redirect
rules (openPortal :376), and shuttles bytes between accepted client
connections and a load-balanced backend endpoint
(pkg/proxy/proxysocket.go TCP copy loop, udp_server.go).

The listener sockets and the copy loop here are real; only the DNAT
hop is the in-memory PortalRuleTable (see ruletable.py).
"""

from __future__ import annotations

import socket
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.proxy.roundrobin import (
    ErrMissingEndpoints,
    ErrMissingServiceEntry,
    LoadBalancerRR,
    ServicePortName,
)
from kubernetes_tpu.proxy.ruletable import PortalRule, PortalRuleTable

_BUFSIZE = 65536
_UDP_IDLE_TIMEOUT = 10.0


@dataclass
class ServiceInfo:
    """One proxied service port (reference: proxier.go serviceInfo)."""

    portal_ip: str
    portal_port: int
    protocol: str
    proxy_port: int
    socket: object
    session_affinity: str = "None"
    node_port: int = 0
    is_alive: bool = True
    real: bool = False  # listener bound at the VIP itself (portal.py)
    node_socket: object = None  # extra listener at the node port itself
    threads: List[threading.Thread] = field(default_factory=list)


class Proxier:
    """Owns one listener socket per (service, port)."""

    def __init__(
        self,
        load_balancer: Optional[LoadBalancerRR] = None,
        rule_table: Optional[PortalRuleTable] = None,
        listen_ip: str = "127.0.0.1",
        real_portals: bool = False,
    ):
        # `is None` checks: an empty PortalRuleTable is falsy (__len__).
        self.lb = load_balancer if load_balancer is not None else LoadBalancerRR()
        self.rules = rule_table if rule_table is not None else PortalRuleTable()
        self.listen_ip = listen_ip
        # Real portals (portal.py): install each service VIP on lo and
        # bind the listener AT clusterIP:port, so clients dial the VIP
        # directly (the openPortal/iptables analog made literal).
        # Per-service fallback to the ephemeral-listener + rule-table
        # mode when the address can't be installed or bound.
        self._portals = None
        if real_portals:
            from kubernetes_tpu.proxy.portal import LoopbackPortals

            if LoopbackPortals.supported():
                self._portals = LoopbackPortals()
        self._lock = threading.Lock()
        self._services: Dict[ServicePortName, ServiceInfo] = {}
        self._stopped = False

    # -- lifecycle ----------------------------------------------------

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
            infos = list(self._services.items())
            self._services.clear()
        for name, info in infos:
            self._close_service(name, info)
        if self._portals is not None:
            self._portals.release_all()

    # -- desired state ------------------------------------------------

    def on_update(self, services: List) -> None:
        """Reconcile running portals against the full service list
        (reference: Proxier.OnUpdate, proxier.go:264-321)."""
        active: Dict[ServicePortName, object] = {}
        for svc in services:
            if not svc.spec.cluster_ip or svc.spec.cluster_ip == "None":
                continue  # headless: no portal
            ns = svc.metadata.namespace or "default"
            for port in svc.spec.ports:
                name: ServicePortName = (ns, svc.metadata.name, port.name)
                active[name] = (svc, port)
        with self._lock:
            if self._stopped:
                return
            to_close = {
                name: info
                for name, info in self._services.items()
                if name not in active
            }
            for name in to_close:
                del self._services[name]
        for name, info in to_close.items():
            self._close_service(name, info, drop_lb=True)
        for name, (svc, port) in active.items():
            self._ensure_service(name, svc, port)

    def service_info(self, name: ServicePortName) -> Optional[ServiceInfo]:
        with self._lock:
            return self._services.get(name)

    def _ensure_service(self, name: ServicePortName, svc, port) -> None:
        # The whole create/reconfigure path runs under the lock:
        # check-then-act with the lock released in between let two
        # threads (informer handler + ProxyServer.start priming) both
        # open a listener for the same service, leaking the loser's
        # socket and accept thread (advisor finding r1). Creation is
        # rare and cheap (local bind); the data path doesn't take this
        # lock.
        with self._lock:
            self._ensure_service_locked(name, svc, port)

    def _ensure_service_locked(self, name: ServicePortName, svc, port) -> None:
        if self._stopped:
            # stop() may have run between on_update's check and this
            # acquisition; creating a portal now would leak its socket
            # and accept thread past shutdown.
            return
        info = self._services.get(name)
        if info is not None:
            if (
                info.portal_ip == svc.spec.cluster_ip
                and info.portal_port == port.port
                and info.protocol == port.protocol.upper()
                and info.session_affinity == (svc.spec.session_affinity or "None")
                and info.node_port == getattr(port, "node_port", 0)
            ):
                # Unchanged spec — but a node-port bind that lost its
                # port to a squatter retries on every sync, so the
                # degradation heals once the port frees up.
                if info.node_port and info.node_socket is None:
                    try:
                        info.node_socket = self._open_socket(
                            info.protocol, self.listen_ip, info.node_port
                        )
                    except OSError:
                        return
                    serve = (
                        self._tcp_accept_loop
                        if info.protocol == "TCP"
                        else self._udp_loop
                    )
                    t = threading.Thread(
                        target=serve,
                        args=(name, info, info.node_socket),
                        daemon=True,
                    )
                    info.threads.append(t)
                    t.start()
                return
            # Reconfiguration: tear down the portal but KEEP the load
            # balancer's endpoint list — endpoints didn't change, and a
            # fresh empty entry would blackhole until the next
            # endpoints event.
            self._close_service(name, info, drop_lb=False)
        proto = port.protocol.upper()
        sock, real = self._open_portal_socket(
            proto, svc.spec.cluster_ip, port.port
        )
        proxy_ip = svc.spec.cluster_ip if real else self.listen_ip
        proxy_port = sock.getsockname()[1]
        info = ServiceInfo(
            portal_ip=svc.spec.cluster_ip,
            portal_port=port.port,
            protocol=proto,
            proxy_port=proxy_port,
            socket=sock,
            session_affinity=svc.spec.session_affinity or "None",
            node_port=getattr(port, "node_port", 0),
            real=real,
        )
        self.lb.new_service(name, affinity_type=info.session_affinity)
        self.rules.ensure_rule(
            PortalRule(
                portal_ip=info.portal_ip,
                portal_port=info.portal_port,
                protocol=proto,
                proxy_ip=proxy_ip,
                proxy_port=proxy_port,
                service=f"{name[0]}/{name[1]}:{name[2]}",
            )
        )
        # NodePort: an extra rule on the node's own address (reference
        # proxier.go openNodePort) PLUS a real listener at the node
        # port itself — the analog of the iptables redirect that makes
        # nodeAddr:nodePort actually accept traffic. Bind failure
        # (port squatted) degrades to the rule-only entry.
        if info.node_port:
            self.rules.ensure_rule(
                PortalRule(
                    portal_ip="0.0.0.0",
                    portal_port=info.node_port,
                    protocol=proto,
                    # Must point where the listener actually is — with
                    # a real portal that is the VIP itself.
                    proxy_ip=proxy_ip,
                    proxy_port=proxy_port,
                    service=f"{name[0]}/{name[1]}:{name[2]}",
                )
            )
            try:
                info.node_socket = self._open_socket(
                    proto, self.listen_ip, info.node_port
                )
            except OSError:
                info.node_socket = None
        serve = self._tcp_accept_loop if proto == "TCP" else self._udp_loop
        socks = [sock] + ([info.node_socket] if info.node_socket else [])
        for s in socks:
            accept = threading.Thread(
                target=serve, args=(name, info, s), daemon=True
            )
            info.threads.append(accept)
        self._services[name] = info
        for t in info.threads:
            t.start()

    @property
    def has_real_portals(self) -> bool:
        """Whether VIP-bound portals are available in this proxier."""
        return self._portals is not None

    def _open_socket(self, proto: str, ip: str = "", port: int = 0):
        kind = socket.SOCK_STREAM if proto == "TCP" else socket.SOCK_DGRAM
        sock = socket.socket(socket.AF_INET, kind)
        # No SO_REUSEADDR on fixed-port UDP binds: two REUSEADDR UDP
        # sockets can both bind the same addr:port with datagrams going
        # to only one of them — the bind must FAIL (degrade to the
        # rule-table entry) rather than silently steal or lose traffic.
        if not (kind == socket.SOCK_DGRAM and port):
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            sock.bind((ip or self.listen_ip, port))
            if proto == "TCP":
                sock.listen(64)
        except OSError:
            sock.close()
            raise
        return sock

    def _open_portal_socket(self, proto: str, cluster_ip: str, port: int):
        """(socket, real): bind AT the VIP when real portals are on and
        the address can be installed; otherwise the classic ephemeral
        listener on listen_ip with the rule table carrying the DNAT."""
        if self._portals is not None and self._portals.acquire(cluster_ip):
            try:
                return self._open_socket(proto, cluster_ip, port), True
            except OSError:
                self._portals.release(cluster_ip)
        return self._open_socket(proto), False

    def _close_service(
        self, name: ServicePortName, info: ServiceInfo, drop_lb: bool = True
    ) -> None:
        info.is_alive = False
        self.rules.delete_rule(info.portal_ip, info.portal_port, info.protocol)
        if info.node_port:
            self.rules.delete_rule("0.0.0.0", info.node_port, info.protocol)
        if drop_lb:
            self.lb.delete_service(name)
        for s in (info.socket, info.node_socket):
            if s is None:
                continue
            try:
                s.close()
            except OSError:
                pass
        if info.real and self._portals is not None:
            self._portals.release(info.portal_ip)

    # -- TCP path (reference: proxysocket.go ProxyLoop + proxyTCP) ----

    def _tcp_accept_loop(
        self, name: ServicePortName, info: ServiceInfo, sock=None
    ) -> None:
        sock = sock if sock is not None else info.socket
        while info.is_alive:
            try:
                client, addr = sock.accept()
            except OSError:
                return
            # Backend dialing happens on a per-connection thread: the
            # up-to-2s endpoint wait must never head-of-line block the
            # accept loop (10 clients arriving during an endpoint gap
            # would otherwise serialize ~2s each behind one accept).
            threading.Thread(
                target=self._serve_connection,
                args=(name, info, client, addr),
                daemon=True,
            ).start()

    def _serve_connection(
        self, name: ServicePortName, info: ServiceInfo, client, addr
    ) -> None:
        try:
            backend = self._connect_backend_wait(name, info, addr[0])
        except (ErrMissingServiceEntry, ErrMissingEndpoints, OSError):
            client.close()
            return
        for a, b in ((client, backend), (backend, client)):
            threading.Thread(
                target=self._copy_bytes, args=(a, b), daemon=True
            ).start()

    def _connect_backend_wait(
        self,
        name: ServicePortName,
        info: ServiceInfo,
        client_ip: str,
        wait: float = 2.0,
    ):
        """_connect_backend, waiting out brief backend gaps: endpoints
        repopulate milliseconds after a readiness flap, and a freshly
        started pod's server may bind a beat after its endpoint is
        published — in both windows dropping an accepted connection
        loses requests a client already queued behind a successful
        portal connect. The reference's tryConnect similarly retries
        dialing with backoff instead of failing the session on the
        first error (proxysocket.go endpointDialTimeout ladder)."""
        deadline = time.monotonic() + wait
        while True:
            try:
                return self._connect_backend(name, client_ip)
            except (ErrMissingServiceEntry, ErrMissingEndpoints, OSError):
                if time.monotonic() >= deadline or not info.is_alive:
                    raise
                time.sleep(0.05)

    def _connect_backend(self, name: ServicePortName, client_ip: str):
        # Retry across endpoints like the reference's tryConnect
        # (proxysocket.go): a dead backend shouldn't fail the session
        # while others remain.
        last_err: Optional[Exception] = None
        for _ in range(max(1, len(self.lb.endpoints_for(name)))):
            endpoint = self.lb.next_endpoint(name, client_ip)
            host, _, port = endpoint.rpartition(":")
            try:
                return socket.create_connection((host, int(port)), timeout=5)
            except OSError as e:
                last_err = e
                # A sticky (ClientIP-affinity) client would otherwise
                # get the same dead endpoint back on every retry.
                self.lb.invalidate_affinity(name, client_ip)
        raise last_err if last_err else OSError("no endpoints")

    @staticmethod
    def _copy_bytes(src, dst) -> None:
        try:
            while True:
                data = src.recv(_BUFSIZE)
                if not data:
                    break
                dst.sendall(data)
        except OSError:
            pass
        finally:
            for s in (src, dst):
                try:
                    s.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    s.close()
                except OSError:
                    pass

    # -- UDP path (reference: udp_server.go / proxysocket.go UDP) -----

    def _udp_loop(
        self, name: ServicePortName, info: ServiceInfo, sock=None
    ) -> None:
        # client addr -> backend socket: UDP "sessions" keyed on the
        # 5-tuple, as the reference's activeClients map does. `sock` is
        # the ingress socket this loop serves (portal or node port);
        # replies must leave through the same one.
        sock = sock if sock is not None else info.socket
        sessions: Dict[Tuple[str, int], socket.socket] = {}

        def reply_loop(client_addr, backend_sock):
            backend_sock.settimeout(_UDP_IDLE_TIMEOUT)
            try:
                while info.is_alive:
                    data = backend_sock.recv(_BUFSIZE)
                    if not data:
                        break
                    sock.sendto(data, client_addr)
            except OSError:
                pass
            finally:
                sessions.pop(client_addr, None)
                try:
                    backend_sock.close()
                except OSError:
                    pass

        while info.is_alive:
            try:
                data, client_addr = sock.recvfrom(_BUFSIZE)
            except OSError:
                return
            backend_sock = sessions.get(client_addr)
            if backend_sock is None:
                try:
                    endpoint = self.lb.next_endpoint(name, client_addr[0])
                except (ErrMissingServiceEntry, ErrMissingEndpoints):
                    continue
                host, _, port = endpoint.rpartition(":")
                backend_sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
                backend_sock.connect((host, int(port)))
                sessions[client_addr] = backend_sock
                # Not tracked in info.threads: reply loops are
                # per-session and self-clean in their finally block.
                threading.Thread(
                    target=reply_loop, args=(client_addr, backend_sock),
                    daemon=True,
                ).start()
            try:
                backend_sock.send(data)
            except OSError:
                sessions.pop(client_addr, None)
