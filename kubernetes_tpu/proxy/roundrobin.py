"""Round-robin endpoint load balancer with session affinity.

Reference: pkg/proxy/roundrobin.go — LoadBalancerRR keeps a per
service-port endpoint list plus a rotating index; NextEndpoint
(:54-77) returns the next endpoint, honoring ClientIP session
affinity with a TTL (affinity state per service, roundrobin.go
affinityState / affinityPolicy).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

# Service-port key: (namespace, service-name, port-name).
ServicePortName = Tuple[str, str, str]


class ErrMissingServiceEntry(KeyError):
    pass


class ErrMissingEndpoints(KeyError):
    pass


@dataclass
class _AffinityState:
    """One sticky client (reference: roundrobin.go affinityState)."""

    client_ip: str
    endpoint: str
    last_used: float = field(default_factory=time.monotonic)


@dataclass
class _BalancerState:
    endpoints: List[str] = field(default_factory=list)
    index: int = 0
    affinity_type: str = "None"  # None | ClientIP
    ttl_seconds: int = 180 * 60  # reference default: 3 hours
    affinity_map: Dict[str, _AffinityState] = field(default_factory=dict)


class LoadBalancerRR:
    """Round-robin with optional ClientIP affinity."""

    def __init__(self):
        self._lock = threading.Lock()
        self._services: Dict[ServicePortName, _BalancerState] = {}

    def new_service(
        self, svc: ServicePortName, affinity_type: str = "None",
        ttl_seconds: int = 0,
    ) -> None:
        """Register a service port (reference: NewService,
        roundrobin.go:88-102)."""
        if ttl_seconds == 0:
            ttl_seconds = 180 * 60
        with self._lock:
            state = self._services.get(svc)
            if state is None:
                self._services[svc] = _BalancerState(
                    affinity_type=affinity_type, ttl_seconds=ttl_seconds
                )
            else:
                state.affinity_type = affinity_type
                state.ttl_seconds = ttl_seconds

    def delete_service(self, svc: ServicePortName) -> None:
        with self._lock:
            self._services.pop(svc, None)

    def next_endpoint(
        self, svc: ServicePortName, client_ip: str = ""
    ) -> str:
        """Pick "host:port" for one new connection (reference:
        NextEndpoint, roundrobin.go:54-77 + affinity check)."""
        with self._lock:
            state = self._services.get(svc)
            if state is None:
                raise ErrMissingServiceEntry(svc)
            if not state.endpoints:
                raise ErrMissingEndpoints(svc)
            if state.affinity_type == "ClientIP" and client_ip:
                aff = state.affinity_map.get(client_ip)
                if aff is not None:
                    if (
                        time.monotonic() - aff.last_used < state.ttl_seconds
                        and aff.endpoint in state.endpoints
                    ):
                        aff.last_used = time.monotonic()
                        return aff.endpoint
                    del state.affinity_map[client_ip]
            endpoint = state.endpoints[state.index]
            state.index = (state.index + 1) % len(state.endpoints)
            if state.affinity_type == "ClientIP" and client_ip:
                state.affinity_map[client_ip] = _AffinityState(
                    client_ip=client_ip, endpoint=endpoint
                )
            return endpoint

    def on_update(self, endpoints_list: List) -> None:
        """Full-state endpoints update (reference: OnUpdate,
        roundrobin.go:134-177): rebuild per service-port endpoint
        lists; registered services missing from the update lose their
        endpoints."""
        seen: Dict[ServicePortName, List[str]] = {}
        for ep in endpoints_list:
            ns = ep.metadata.namespace or "default"
            name = ep.metadata.name
            for subset in ep.subsets:
                for port in subset.ports:
                    key = (ns, name, port.name)
                    eps = seen.setdefault(key, [])
                    for addr in subset.addresses:
                        eps.append(f"{addr.ip}:{port.port}")
        with self._lock:
            for key, eps in seen.items():
                state = self._services.get(key)
                if state is None:
                    state = self._services[key] = _BalancerState()
                if sorted(state.endpoints) != sorted(eps):
                    state.endpoints = eps
                    state.index = 0
                    # Stale affinity entries pointing at removed
                    # endpoints are dropped lazily in next_endpoint.
        # The update is the full desired state: any registered
        # service-port key absent from it has no endpoints anymore —
        # including a single named port dropped from an Endpoints
        # object whose other ports remain (reference: roundrobin.go
        # OnUpdate removes every key missing from the update).
        with self._lock:
            for key, state in self._services.items():
                if key not in seen and state.endpoints:
                    state.endpoints = []
                    state.index = 0

    def endpoints_for(self, svc: ServicePortName) -> List[str]:
        with self._lock:
            state = self._services.get(svc)
            return list(state.endpoints) if state else []

    def invalidate_affinity(self, svc: ServicePortName, client_ip: str) -> None:
        """Drop one client's sticky endpoint (used by the proxier when
        a connect to it fails, so retries rotate to live backends)."""
        with self._lock:
            state = self._services.get(svc)
            if state is not None and client_ip:
                state.affinity_map.pop(client_ip, None)

    def clean_expired_affinity(self) -> None:
        """Drop affinity entries past their TTL."""
        now = time.monotonic()
        with self._lock:
            for state in self._services.values():
                dead = [
                    ip
                    for ip, aff in state.affinity_map.items()
                    if now - aff.last_used >= state.ttl_seconds
                ]
                for ip in dead:
                    del state.affinity_map[ip]
