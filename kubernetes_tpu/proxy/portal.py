"""Real service portals: cluster VIPs installed on the loopback device.

The reference's openPortal (pkg/proxy/proxier.go:376) installs
iptables DNAT rules so a connection to clusterIP:port lands on the
proxier's socket. The userspace analog here goes one step simpler and
just as real: add the service's cluster IP as a /32 address on `lo`
(root / CAP_NET_ADMIN), then bind the proxier's listener DIRECTLY to
(clusterIP, port). Any process on the host can then dial the VIP — the
guestbook frontend's REDIS_MASTER_SERVICE_HOST works verbatim — with
no NAT hop at all.

Addresses are refcounted per IP (many service ports can share one
cluster IP) and removed when the last user releases them or the
proxier stops.
"""

from __future__ import annotations

import subprocess
import threading
from typing import Dict, Optional


def _ip(*args: str) -> subprocess.CompletedProcess:
    return subprocess.run(
        ["ip", *args], capture_output=True, text=True, timeout=10
    )


def _addr_exists(stderr: str) -> bool:
    """`ip addr add` duplicate-address message varies by iproute2
    version: 'RTNETLINK answers: File exists' (classic) vs
    'Error: ipv4: Address already assigned.' (newer). Both mean the
    address is present and usable."""
    return "File exists" in stderr or "already assigned" in stderr


class LoopbackPortals:
    """Refcounted /32 loopback addresses for service VIPs."""

    _supported: Optional[bool] = None
    _probe_lock = threading.Lock()

    def __init__(self):
        self._refs: Dict[str, int] = {}
        # Whether WE installed the address (vs adopting a pre-existing
        # one): only ours get deleted on release — tearing down an
        # address some other process installed would cut its live
        # listeners off the VIP.
        self._owned: Dict[str, bool] = {}
        self._lock = threading.Lock()

    @classmethod
    def supported(cls) -> bool:
        """One probe per process: can we add/remove lo addresses?"""
        with cls._probe_lock:
            if cls._supported is None:
                probe = "10.255.254.253"
                try:
                    add = _ip("addr", "add", f"{probe}/32", "dev", "lo")
                    ok = add.returncode == 0 or _addr_exists(add.stderr)
                    if add.returncode == 0:
                        _ip("addr", "del", f"{probe}/32", "dev", "lo")
                    cls._supported = ok
                except (OSError, subprocess.TimeoutExpired):
                    cls._supported = False
            return cls._supported

    def acquire(self, ip: str) -> bool:
        """Ensure `ip` exists on lo; returns success."""
        with self._lock:
            if self._refs.get(ip, 0) > 0:
                self._refs[ip] += 1
                return True
            try:
                out = _ip("addr", "add", f"{ip}/32", "dev", "lo")
            except (OSError, subprocess.TimeoutExpired):
                return False
            if out.returncode == 0:
                owned = True
            elif _addr_exists(out.stderr):
                owned = False  # pre-existing: usable but not ours
            else:
                return False
            self._refs[ip] = 1
            self._owned[ip] = owned
            return True

    def _del_if_owned(self, ip: str, owned: bool) -> None:
        if not owned:
            return
        try:
            _ip("addr", "del", f"{ip}/32", "dev", "lo")
        except (OSError, subprocess.TimeoutExpired):
            pass

    def release(self, ip: str) -> None:
        # The `ip addr del` runs UNDER the lock: dropping it first
        # would let a concurrent acquire() adopt the still-present
        # address ('File exists', owned=False) and bind a listener the
        # delete then cuts off the VIP forever. Releases are rare and
        # the subprocess is milliseconds.
        with self._lock:
            n = self._refs.get(ip, 0)
            if n > 1:
                self._refs[ip] = n - 1
                return
            self._refs.pop(ip, None)
            owned = self._owned.pop(ip, False)
            self._del_if_owned(ip, owned)

    def release_all(self) -> None:
        with self._lock:
            pairs = [(ip, self._owned.get(ip, False)) for ip in self._refs]
            self._refs.clear()
            self._owned.clear()
            for ip, owned in pairs:
                self._del_if_owned(ip, owned)
